"""NodePool auxiliary controllers + node health (repair).

Reference /root/reference/pkg/controllers/nodepool/{hash,counter,readiness,
registrationhealth,validation} and node/health/controller.go:106-203.
"""

from __future__ import annotations

import re
from collections import deque
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import COND_NODE_CLASS_READY, COND_NODE_REGISTRATION_HEALTHY
from karpenter_tpu.controllers.kube import Conflict, NotFound, SimKube
from karpenter_tpu.controllers.nodeclaim_aux import NODEPOOL_HASH_VERSION, nodepool_hash
from karpenter_tpu.controllers.state import Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.utils import resources as res
from karpenter_tpu import metrics

NODEPOOL_USAGE = metrics.REGISTRY.gauge(
    "karpenter_nodepools_usage",
    "Resource usage per nodepool.",
    ("nodepool", "resource_type"),
)
NODEPOOL_NODE_COUNT = metrics.REGISTRY.gauge(
    "karpenter_nodepools_node_count", "Node count per nodepool.", ("nodepool",)
)
NODES_REPAIRED = metrics.REGISTRY.counter(
    "karpenter_nodes_repaired_total", "Nodes force-deleted by auto-repair.", ("condition",)
)


class NodePoolHash:
    """nodepool/hash: propagate the drift hash onto the NodePool annotations
    (hash/controller.go:55). NodeClaims pick it up at hydration/creation."""

    def __init__(self, kube: SimKube):
        self.kube = kube

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            want = nodepool_hash(np)
            ann = np.metadata.annotations
            if (
                ann.get(well_known.NODEPOOL_HASH_ANNOTATION_KEY) == want
                and ann.get(well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY)
                == NODEPOOL_HASH_VERSION
            ):
                continue
            ann[well_known.NODEPOOL_HASH_ANNOTATION_KEY] = want
            ann[well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = NODEPOOL_HASH_VERSION
            try:
                self.kube.update("NodePool", np)
            except (Conflict, NotFound):
                pass


class NodePoolCounter:
    """nodepool/counter: aggregate owned node resources into NodePool status
    (counter/controller.go:70)."""

    def __init__(self, kube: SimKube, cluster: Cluster):
        self.kube = kube
        self.cluster = cluster

    def reconcile_all(self) -> None:
        totals: dict[str, dict] = {}
        counts: dict[str, int] = {}
        for sn in self.cluster.state_nodes():
            np_name = sn.nodepool_name
            if np_name is None:
                continue
            totals[np_name] = res.merge(totals.get(np_name, {}), sn.capacity())
            counts[np_name] = counts.get(np_name, 0) + 1
        for np in self.kube.list("NodePool"):
            want_res = totals.get(np.name, {})
            want_count = counts.get(np.name, 0)
            if np.status_resources == want_res and np.status_node_count == want_count:
                continue
            np.status_resources = want_res
            np.status_node_count = want_count
            try:
                self.kube.update("NodePool", np)
            except (Conflict, NotFound):
                continue
            NODEPOOL_NODE_COUNT.set(float(want_count), {"nodepool": np.name})
            for rname, v in want_res.items():
                NODEPOOL_USAGE.set(
                    float(v), {"nodepool": np.name, "resource_type": rname}
                )


class NodePoolReadiness:
    """nodepool/readiness: NodeClassReady condition (readiness/controller.go:53).
    In-tree providers have no external NodeClass objects, so readiness is a
    provider callback (ready unless the provider objects)."""

    def __init__(self, kube: SimKube, cloud):
        self.kube = kube
        self.cloud = cloud

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            ready = True
            checker = getattr(self.cloud, "node_class_ready", None)
            if checker is not None:
                ready = bool(checker(np))
            want = "True" if ready else "False"
            if np.conditions.get(COND_NODE_CLASS_READY) != want:
                np.conditions[COND_NODE_CLASS_READY] = want
                try:
                    self.kube.update("NodePool", np)
                except (Conflict, NotFound):
                    pass


class RegistrationHealth:
    """nodepool/registrationhealth + pkg/state/nodepoolhealth: the
    NodeRegistrationHealthy condition driven by a fixed ring buffer of
    registration outcomes. Reference tracker semantics exactly
    (tracker.go:27-81): buffer of 4, status Unknown while empty, Unhealthy
    when falses/4 >= 0.5 (the DENOMINATOR is the buffer capacity even when
    partially filled), else Healthy. Condition flips happen at observation
    time through a dry-run of the would-be buffer (registration.go:113-123
    on success, liveness.go:128-157 on a registration timeout) — not by a
    periodic sweep. reconcile_all mirrors the nodepool controller
    (registrationhealth/controller.go:73-89): re-hydrate the buffer from
    a surviving condition after restart, and reset to Unknown when the
    NodePool spec changed (the drift hash stands in for generation)."""

    BUFFER = 4  # tracker.go:27 BufferSize
    THRESHOLD = 0.5  # tracker.go:29 ThresholdFalse

    UNKNOWN, HEALTHY, UNHEALTHY = "Unknown", "Healthy", "Unhealthy"

    def __init__(self, kube: SimKube):
        self.kube = kube
        self._buf: dict[str, deque] = {}
        self._observed_hash: dict[str, str] = {}

    # -- tracker (pkg/state/nodepoolhealth/tracker.go) --------------------

    def _buffer(self, nodepool: str) -> deque:
        return self._buf.setdefault(nodepool, deque(maxlen=self.BUFFER))

    def _status_of(self, items) -> str:
        if not items:
            return self.UNKNOWN
        falses = sum(1 for v in items if not v)
        if falses / self.BUFFER >= self.THRESHOLD:
            return self.UNHEALTHY
        return self.HEALTHY

    def status(self, nodepool: str) -> str:
        return self._status_of(self._buf.get(nodepool) or ())

    def dry_run(self, nodepool: str, ok: bool) -> str:
        """tracker.go DryRun: status if `ok` were inserted now."""
        items = list(self._buf.get(nodepool) or ())[-(self.BUFFER - 1):]
        return self._status_of(items + [ok])

    def set_status(self, nodepool: str, status: str) -> None:
        buf = self._buffer(nodepool)
        buf.clear()
        if status == self.HEALTHY:
            buf.append(True)
        elif status == self.UNHEALTHY:
            for _ in range(int(self.BUFFER * self.THRESHOLD)):
                buf.append(False)

    # -- observation entry point (lifecycle controller calls this) --------

    def record_launch(self, nodepool: str, ok: bool) -> None:
        """A registration outcome: success when the claim registered
        (registration.go:123), failure when the liveness TTL deleted it
        first (liveness.go:156). Flips the NodePool condition when the
        dry-run crosses the threshold, THEN commits the observation —
        the reference's exact order."""
        np = self.kube.try_get("NodePool", nodepool)
        if np is not None:
            cond = np.conditions.get(COND_NODE_REGISTRATION_HEALTHY)
            want = None
            if ok and cond != "True" and (
                self.dry_run(nodepool, True) == self.HEALTHY
            ):
                want = "True"
            elif not ok and cond != "False" and (
                self.dry_run(nodepool, False) == self.UNHEALTHY
            ):
                want = "False"
            if want is not None:
                np.conditions[COND_NODE_REGISTRATION_HEALTHY] = want
                try:
                    self.kube.update("NodePool", np)
                except (Conflict, NotFound):
                    pass
        self._buffer(nodepool).append(ok)

    # -- the nodepool controller sweep ------------------------------------

    def reconcile_all(self) -> None:
        for np in self.kube.list("NodePool"):
            cond = np.conditions.get(COND_NODE_REGISTRATION_HEALTHY)
            # restart hydration (registrationhealth/controller.go:73-80)
            if self.status(np.name) == self.UNKNOWN and cond in ("True", "False"):
                self.set_status(
                    np.name, self.HEALTHY if cond == "True" else self.UNHEALTHY
                )
            # spec change resets to Unknown (controller.go:83-88; the drift
            # hash is this model's generation)
            h = nodepool_hash(np)
            prev = self._observed_hash.get(np.name)
            self._observed_hash[np.name] = h
            if prev is not None and prev != h:
                self.set_status(np.name, self.UNKNOWN)
                if cond != "Unknown":
                    np.conditions[COND_NODE_REGISTRATION_HEALTHY] = "Unknown"
                    try:
                        self.kube.update("NodePool", np)
                    except (Conflict, NotFound):
                        pass


# -- CEL-equivalent validators (nodepool.go markers; helpers shared by
# NodePoolValidation.validate) ------------------------------------------------

_NAME_RE = re.compile(r"^[A-Za-z0-9]([A-Za-z0-9._-]*[A-Za-z0-9])?$")
_DNS1123_RE = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?(\.[a-z0-9]([a-z0-9-]*[a-z0-9])?)*$")
# Budget.Nodes (nodepool.go:122): 0-100% or a non-negative integer
_BUDGET_NODES_RE = re.compile(r"^((100|[0-9]{1,2})%|[0-9]+)$")
_CRON_SPECIALS = frozenset(
    {"@annually", "@yearly", "@monthly", "@weekly", "@daily", "@midnight", "@hourly"}
)


def _qualified_name_err(key: str) -> Optional[str]:
    """k8s.io/apimachinery validation.IsQualifiedName: [prefix/]name with a
    DNS-1123-subdomain prefix <= 253 chars and a name part <= 63."""
    if not key:
        return "name part must be non-empty"
    parts = key.split("/")
    if len(parts) > 2:
        return "a qualified name must consist of alphanumeric characters"
    if len(parts) == 2:
        prefix, name = parts
        if not prefix:
            return "prefix part must be non-empty"
        if len(prefix) > 253:
            return "prefix part must be no more than 253 characters"
        if not _DNS1123_RE.match(prefix):
            return "prefix part must be a DNS-1123 subdomain"
    else:
        name = parts[0]
    if not name:
        return "name part must be non-empty"
    if len(name) > 63:
        return "name part must be no more than 63 characters"
    if not _NAME_RE.match(name):
        return (
            "name part must consist of alphanumeric characters, '-', '_' "
            "or '.', and must start and end with an alphanumeric character"
        )
    return None


def _label_value_err(value: str) -> Optional[str]:
    if value == "":
        return None
    if len(value) > 63:
        return "must be no more than 63 characters"
    if not _NAME_RE.match(value):
        return (
            "a valid label value must be an empty string or consist of "
            "alphanumeric characters, '-', '_' or '.'"
        )
    return None


def _validate_template_labels(labels: dict) -> Optional[str]:
    """nodepool_validation.go:33 validateLabels."""
    for key, value in labels.items():
        if key == well_known.NODEPOOL_LABEL_KEY:
            return f"invalid key name {key!r} in labels, restricted"
        err = _qualified_name_err(key)
        if err:
            return f"invalid key name {key!r} in labels, {err}"
        err = _label_value_err(value)
        if err:
            return f"invalid value: {value} for label[{key}], {err}"
        err = well_known.is_restricted_label(key)
        if err:
            return f"invalid key name {key!r} in labels, {err}"
    return None


def _validate_taint(taint) -> Optional[str]:
    """CEL taint rules (nodepool.go taints markers + CEL test families):
    key required + qualified, value a valid label value, effect one of the
    three kubelet effects."""
    if not taint.key:
        return "taint key is required"
    err = _qualified_name_err(taint.key)
    if err:
        return f"invalid taint key {taint.key!r}, {err}"
    err = _label_value_err(taint.value)
    if err:
        return f"invalid taint value {taint.value!r}, {err}"
    if str(getattr(taint.effect, "value", taint.effect)) not in (
        "NoSchedule", "PreferNoSchedule", "NoExecute",
    ):
        return f"invalid taint effect {taint.effect!r}"
    return None


_SUPPORTED_OPS = frozenset(
    {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"}
)


def validate_requirement(r) -> Optional[str]:
    """nodeclaim_validation.go:115 ValidateRequirement, shared by the
    NodePool template validator and the provisioner's per-pod selector
    validation (provisioner.go:573 validateNodeSelectorTerm): normalized
    key, supported operator, restricted-label check, qualified name, label
    values, In non-empty, minValues bounds, Gt/Lt integer shape, and
    well-known value sets."""
    key = well_known.NORMALIZED_LABELS.get(r.key, r.key)
    err = _qualified_name_err(key)
    if err:
        return f"key {key} is not a qualified name, {err}"
    err = well_known.is_restricted_label(key)
    if err:
        return err
    op = str(getattr(r.operator, "value", r.operator))
    if op not in _SUPPORTED_OPS:
        return f"key {key} has an unsupported operator {op}"
    for v in r.values:
        err = _label_value_err(v)
        if err:
            return f"invalid value {v} for key {key}, {err}"
    if op == "In" and not r.values:
        return f"key {key} with operator 'In' must have a value defined"
    if op in ("Gt", "Lt"):
        ok = len(r.values) == 1
        if ok:
            try:
                ok = int(r.values[0]) >= 0
            except ValueError:
                ok = False
        if not ok:
            return (
                f"key {key} with operator {op!r} must have a single "
                "positive integer value"
            )
    mv = getattr(r, "min_values", None)
    if mv is not None:
        if mv < 1:
            return "minValues must be at least 1"
        if mv > 50:
            return "minValues must be no more than 50"
        # raw length, no dedup (nodeclaim_validation.go:142 compares
        # len(Values) directly)
        if op == "In" and len(r.values) < mv:
            return (
                "requirements with 'minValues' must have at least that many "
                "values specified in the 'values' field"
            )
    # validateWellKnownValues (nodeclaim_validation.go:164-191): an In set
    # for a key with a known value universe must keep at least one known
    # value — and at least minValues of them when minValues is set
    known = well_known.WELL_KNOWN_VALUES_FOR_REQUIREMENTS.get(key)
    if known is not None and op == "In" and r.values:
        valid = [v for v in r.values if v in known]
        if not valid:
            return (
                f"no valid values found in {r.values} for {key}, expected "
                f"one of: {sorted(known)}"
            )
        if mv is not None and len(valid) < mv:
            return (
                f"only {len(valid)} valid values found in {r.values} for "
                f"{key}, expected at least {mv}"
            )
    return None


def _validate_requirement(r) -> Optional[str]:
    """Template-side requirement rules: ValidateRequirement plus the
    nodepool-key rejection (nodepool_validation.go:50
    validateRequirementsNodePoolKeyDoesNotExist)."""
    if r.key == well_known.NODEPOOL_LABEL_KEY:
        return f"invalid key: {r.key!r} in requirements, restricted"
    return validate_requirement(r)


def _valid_cron(expr: str) -> bool:
    """The CRD's schedule pattern (nodepool.go:129): an @special or five
    whitespace-separated fields. Deliberately permissive — name-based
    fields like \"MON-FRI\" are valid cron; full parsing happens where
    schedules are evaluated, exactly as the reference defers to
    cron.ParseStandard at use time."""
    expr = expr.strip()
    if expr.startswith("@"):
        return expr in _CRON_SPECIALS
    return len(expr.split()) == 5


def _validate_budget(budget) -> Optional[str]:
    """Budget CEL rules: nodes pattern (nodepool.go:122), schedule cron
    (nodepool.go:129), duration without seconds (nodepool.go:138), and
    'schedule must be set with duration' (nodepool.go:99)."""
    raw = budget.nodes.strip()
    if not _BUDGET_NODES_RE.match(raw):
        return f"invalid budget nodes value: {raw!r}"
    has_schedule = budget.schedule is not None
    has_duration = budget.duration_seconds is not None
    if has_schedule != has_duration:
        return "'schedule' must be set with 'duration'"
    if has_schedule and not _valid_cron(budget.schedule):
        return f"invalid budget schedule {budget.schedule!r}"
    if has_duration:
        d = budget.duration_seconds
        # the CRD pattern admits hours/minutes only — no seconds, no sign
        if d < 0 or d != int(d) or int(d) % 60 != 0:
            return "budget duration must be a non-negative h/m duration"
    return None


class NodePoolValidation:
    """nodepool/validation: runtime spec validation — the CRD's CEL surface
    absorbed (validation/controller.go:51 + nodepool_validation.go:28)."""

    def __init__(self, kube: SimKube, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.recorder = recorder

    def reconcile_all(self) -> dict[str, str]:
        problems: dict[str, str] = {}
        for np in self.kube.list("NodePool"):
            err = self.validate(np)
            if err is not None:
                problems[np.name] = err
                if self.recorder:
                    self.recorder.publish(
                        Event("NodePool", np.name, "Warning", "FailedValidation", err)
                    )
        return problems

    @staticmethod
    def validate(np) -> Optional[str]:
        """The CRD's CEL rule table + RuntimeValidate, absorbed into one
        runtime check (reference nodepool.go:39-232 XValidation/Pattern
        markers + nodepool_validation.go:28 RuntimeValidate; scenario
        families mirrored from nodepool_validation_cel_test.go). Returns
        the FIRST problem found, reference-ordered: labels, taints,
        requirements, budgets, then scalar fields."""
        err = _validate_template_labels(np.template.labels)
        if err:
            return err
        for taint in list(np.template.taints) + list(np.template.startup_taints):
            err = _validate_taint(taint)
            if err:
                return err
        if len(np.template.requirements) > 100:
            return "requirements exceed the 100-item limit"
        for r in np.template.requirements:
            err = _validate_requirement(r)
            if err:
                return err
        if len(np.disruption.budgets) > 50:
            return "budgets exceed the 50-item limit"
        for budget in np.disruption.budgets:
            err = _validate_budget(budget)
            if err:
                return err
        if np.disruption.consolidate_after_seconds < 0:
            return "consolidateAfter must be >= 0"
        # weight: optional, 1..100 when set (nodepool.go:60-61; 0 = unset)
        if np.weight < 0 or np.weight > 100:
            return "weight must be in [1, 100]"
        if np.replicas is not None:
            # static-pool CEL rules (nodepool.go:39-41)
            if np.replicas < 0:
                return "replicas must be >= 0"
            if np.weight:
                return "'weight' is not supported on static NodePools"
            extra = [k for k in np.limits if k != "nodes"]
            if extra:
                return "only 'limits.nodes' is supported on static NodePools"
        return None


class NodeHealth:
    """node/health: force-delete nodes whose provider repair-policy
    conditions stayed unhealthy past the toleration window
    (health/controller.go:106). Gated by the NodeRepair feature flag."""

    def __init__(self, kube: SimKube, cluster: Cluster, cloud, clock, recorder=None):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock
        self.recorder = recorder
        self._unhealthy_since: dict[tuple[str, str], float] = {}

    def reconcile_all(self) -> int:
        policies = self.cloud.repair_policies()
        if not policies:
            return 0
        repaired = 0
        now = self.clock.now()
        for node in self.kube.list("Node"):
            if node.metadata.deletion_timestamp is not None:
                continue
            for policy in policies:
                key = (node.name, policy.condition_type)
                status = node.conditions.get(policy.condition_type)
                if status != policy.condition_status:
                    self._unhealthy_since.pop(key, None)
                    continue
                since = self._unhealthy_since.setdefault(key, now)
                if now - since < policy.toleration_seconds:
                    continue
                sn = self.cluster.node_by_name(node.name)
                claim = sn.node_claim if sn is not None else None
                if claim is not None:
                    try:
                        self.kube.delete("NodeClaim", claim.name)
                    except NotFound:
                        pass
                else:
                    try:
                        self.kube.delete("Node", node.name)
                    except NotFound:
                        pass
                NODES_REPAIRED.inc({"condition": policy.condition_type})
                if self.recorder:
                    self.recorder.publish(
                        Event(
                            "Node", node.name, "Warning", "NodeRepair",
                            f"condition {policy.condition_type} unhealthy for "
                            f"{now - since:.0f}s; replacing",
                        )
                    )
                repaired += 1
                break
        return repaired
