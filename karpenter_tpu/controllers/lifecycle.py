"""NodeClaim lifecycle: Launch -> Registration -> Initialization + Liveness,
plus the claim termination finalizer.

Reference /root/reference/pkg/controllers/nodeclaim/lifecycle/:
- launch.go:45-124 (CloudProvider.Create, Launched condition)
- registration.go:50-127 (node joins; sync labels/taints; Registered)
- initialization.go:46-134 (startup taints gone, resources present; Initialized)
- liveness.go:51-75 (TTL deletes for stuck claims)
- controller.go:184-273 (termination finalizer: delete instance + node)
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    NodeClaim,
    Operator,
)
from karpenter_tpu.cloudprovider.types import CreateError, NodeClaimNotFoundError
from karpenter_tpu.controllers.kube import Conflict, NotFound, SimKube
from karpenter_tpu.controllers.state import UNREGISTERED_TAINT, Cluster
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.options import Options
from karpenter_tpu import logging, metrics

TERMINATION_FINALIZER = well_known.TERMINATION_FINALIZER

LAUNCH_FAILURES = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_launch_failed_total",
    "NodeClaim launch attempts that failed.",
    ("nodepool", "reason"),
)
CLAIMS_TERMINATED = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_terminated_total",
    "NodeClaims terminated.",
    ("nodepool",),
)


class NodeClaimLifecycle:
    """One reconciler driving the whole claim state machine (the reference
    splits it into sub-reconcilers invoked in order; the order here is the
    same)."""

    def __init__(
        self,
        kube: SimKube,
        cluster: Cluster,
        cloud_provider,
        clock,
        options: Optional[Options] = None,
        recorder: Optional[Recorder] = None,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.opts = options or Options()
        self.recorder = recorder or Recorder(clock)
        # claim name -> first-seen time, for liveness TTLs with FakeClock
        self._first_seen: dict[str, float] = {}
        # optional hook: nodepool registration-health ring buffer
        self.registration_health = None
        self.log = logging.root.named("nodeclaim.lifecycle")

    def reconcile_all(self) -> None:
        for claim in self.kube.list("NodeClaim"):
            self.reconcile(claim.name)

    def reconcile(self, name: str) -> Optional[str]:
        claim = self.kube.try_get("NodeClaim", name)
        if claim is None:
            self._first_seen.pop(name, None)
            return None
        if claim.metadata.deletion_timestamp is not None:
            return self._terminate(claim)
        if TERMINATION_FINALIZER not in claim.metadata.finalizers:
            claim.metadata.finalizers.append(TERMINATION_FINALIZER)
            claim = self._update(claim)
            if claim is None:
                return None
        self._first_seen.setdefault(name, self.clock.now())

        if claim.status.conditions.get(COND_LAUNCHED) != "True":
            return self._launch(claim)
        if claim.status.conditions.get(COND_REGISTERED) != "True":
            return self._register(claim)
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return self._initialize(claim)
        return None

    # -- phases -----------------------------------------------------------

    def _launch(self, claim: NodeClaim) -> Optional[str]:
        try:
            launched = self.cloud.create(claim)
        except CreateError as e:
            nodepool = claim.nodepool_name or ""
            LAUNCH_FAILURES.inc({"nodepool": nodepool, "reason": e.reason})
            self.recorder.publish(
                Event("NodeClaim", claim.name, "Warning", "LaunchFailed", str(e))
            )
            return self._liveness(claim)
        claim.status.provider_id = launched.status.provider_id
        claim.status.node_name = launched.status.node_name
        claim.status.capacity = dict(launched.status.capacity)
        claim.status.allocatable = dict(launched.status.allocatable)
        claim.status.image_id = launched.status.image_id
        # PopulateNodeClaimDetails (launch.go:126-140): cloud-resolved
        # labels, then single-value requirement labels, then user-defined
        # labels — later sources win. RequirementsDrifted diffs these
        # labels against the nodepool's requirements (drift.go:168-174).
        merged = dict(launched.metadata.labels)
        for r in claim.requirements:
            if r.operator == Operator.IN and len(r.values) == 1:
                merged[r.key] = r.values[0]
        merged.update(claim.metadata.labels)
        claim.metadata.labels = merged
        claim.status.conditions[COND_LAUNCHED] = "True"
        self._update(claim)
        self.log.info(
            "launched nodeclaim",
            nodeclaim=claim.name,
            provider_id=claim.status.provider_id,
        )
        return "launched"

    def _register(self, claim: NodeClaim) -> Optional[str]:
        node = self._node_for(claim)
        if node is None:
            return self._liveness(claim)
        # sync: claim labels/annotations flow to the node; the unregistered
        # taint is removed exactly once (registration.go:50-127)
        changed = False
        for k, v in claim.metadata.labels.items():
            if node.metadata.labels.get(k) != v:
                node.metadata.labels[k] = v
                changed = True
        if UNREGISTERED_TAINT in node.taints:
            node.taints = [t for t in node.taints if t != UNREGISTERED_TAINT]
            changed = True
        if node.metadata.labels.get(well_known.NODE_REGISTERED_LABEL_KEY) != "true":
            node.metadata.labels[well_known.NODE_REGISTERED_LABEL_KEY] = "true"
            changed = True
        if changed:
            try:
                self.kube.update("Node", node)
            except (Conflict, NotFound):
                return None  # requeue
        claim.status.node_name = node.name
        claim.status.conditions[COND_REGISTERED] = "True"
        self._update(claim)
        # a successful registration feeds the nodepool health ring
        # (registration.go:113-123: dry-run flip, then commit)
        if self.registration_health is not None:
            self.registration_health.record_launch(claim.nodepool_name or "", True)
        self.recorder.publish(
            Event("NodeClaim", claim.name, "Normal", "Registered", node.name)
        )
        return "registered"

    def _initialize(self, claim: NodeClaim) -> Optional[str]:
        node = self._node_for(claim)
        if node is None:
            return None
        if not node.ready:
            return None
        # startup AND known-ephemeral taints must have been removed
        # (initialization.go:46 StartupTaintsRemoved + :88
        # KnownEphemeralTaintsRemoved — a not-ready/unreachable node is
        # not initialized no matter how ready its kubelet claims to be)
        from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS

        blocked = {
            (t.key, t.effect)
            for t in list(claim.startup_taints) + list(KNOWN_EPHEMERAL_TAINTS)
        }
        if any((t.key, t.effect) in blocked for t in node.taints):
            return None
        # resources registered
        if not node.allocatable:
            return None
        node.metadata.labels[well_known.NODE_INITIALIZED_LABEL_KEY] = "true"
        try:
            self.kube.update("Node", node)
        except (Conflict, NotFound):
            return None
        claim.status.conditions[COND_INITIALIZED] = "True"
        self._update(claim)
        return "initialized"

    def _liveness(self, claim: NodeClaim) -> Optional[str]:
        """liveness.go:51: delete claims stuck before registration."""
        first = self._first_seen.get(claim.name, self.clock.now())
        age = self.clock.now() - first
        launched = claim.status.conditions.get(COND_LAUNCHED) == "True"
        if not launched and age > self.opts.launch_ttl_seconds:
            self.log.warn(
                "liveness TTL exceeded before launch; deleting nodeclaim",
                nodeclaim=claim.name, age_seconds=round(age, 1),
            )
            # a claim that never made it feeds the health ring as a failure
            # (liveness.go:89 + 156: dry-run flip, then commit)
            if self.registration_health is not None:
                self.registration_health.record_launch(
                    claim.nodepool_name or "", False
                )
            self.kube.delete("NodeClaim", claim.name)
            self.recorder.publish(
                Event(
                    "NodeClaim", claim.name, "Warning", "LivenessTimeout",
                    f"not launched after {age:.0f}s",
                )
            )
            return "liveness-deleted"
        if launched and age > self.opts.registration_ttl_seconds:
            self.log.warn(
                "liveness TTL exceeded before registration; deleting nodeclaim",
                nodeclaim=claim.name, age_seconds=round(age, 1),
            )
            if self.registration_health is not None:
                self.registration_health.record_launch(
                    claim.nodepool_name or "", False
                )
            self.kube.delete("NodeClaim", claim.name)
            self.recorder.publish(
                Event(
                    "NodeClaim", claim.name, "Warning", "LivenessTimeout",
                    f"not registered after {age:.0f}s",
                )
            )
            return "liveness-deleted"
        return None

    # -- termination finalizer (controller.go:184) ------------------------

    def _terminate(self, claim: NodeClaim) -> Optional[str]:
        # delete the node first; its own termination finalizer drains it
        node = self._node_for(claim)
        if node is not None and node.metadata.deletion_timestamp is None:
            self.kube.delete("Node", node.name)
            return "awaiting-node-termination"
        if node is not None:
            return "awaiting-node-termination"
        try:
            self.cloud.delete(claim)
        except NodeClaimNotFoundError:
            pass
        if TERMINATION_FINALIZER in claim.metadata.finalizers:
            claim.metadata.finalizers.remove(TERMINATION_FINALIZER)
            try:
                self.kube.update("NodeClaim", claim)
            except (Conflict, NotFound):
                return None
        CLAIMS_TERMINATED.inc({"nodepool": claim.nodepool_name or ""})
        self._first_seen.pop(claim.name, None)
        return "terminated"

    # -- helpers ----------------------------------------------------------

    def _node_for(self, claim: NodeClaim):
        # the cluster cache indexes provider ids; avoid a deep-copy List scan
        sn = self.cluster.node_by_claim_name(claim.name)
        if sn is not None and sn.node is not None:
            return self.kube.try_get("Node", sn.node.name)
        if claim.status.node_name:
            return self.kube.try_get("Node", claim.status.node_name)
        return None

    def _update(self, claim: NodeClaim) -> Optional[NodeClaim]:
        try:
            return self.kube.update("NodeClaim", claim)
        except (Conflict, NotFound):
            return None
