"""Removal-set consolidation: exhaustive batched search over arbitrary
node-removal sets.

The prefix sweep (disruption/sweep.py) batches only CONTIGUOUS prefixes
of the cost-sorted candidate list — the reference's entire search space
(multinodeconsolidation.go:116 firstNConsolidationOption binary-searches
it with ~log2(N) sequential re-simulations under a 1-minute budget and a
100-candidate cap, multinodeconsolidation.go:35,86). Any feasible
removal set that is not a contiguous prefix is structurally unreachable
there: one immovable cheap node early in the cost order shadows every
better set behind it. This module generalizes the delta-state kernel to
an arbitrary per-lane membership bitmask M[B, J] over the candidates:

- **disabled-slot mask**: removed[b, e] = M[b, cand_of_slot[e]] — a
  gather through the slot->candidate index (sentinel J for slots that
  are not candidates), replacing the prefix kernel's lane-index compare;
- **restored counts / valid pods**: counts[b] = base + M[b] @ P, where
  P[j, c] counts candidate j's reschedulable pods of encode class c
  (tpu_problem.group_class_counts) — a device int32 matmul replacing the
  host-side prefix cumsum (which is the lower-triangular special case of
  the same matrix);
- per-lane availability: removed slots go to -1 (fit nothing), then the
  shared class-cumsum FFD core + <=1-new-claim check
  (sweep._ffd_feasibility_core) scores every lane at once.

**int64 guard argument for non-monotone sets** (CLAUDE.md: int32 totals
must never wrap): per-lane totals are no longer prefix-monotone, so the
worst case is a MAX OVER MASKS rather than the longest prefix's total.
But every per-lane count is a sum of NON-NEGATIVE per-candidate
contributions (base >= 0, P >= 0, M in {0,1}), so each lane's counts are
dominated elementwise by the all-candidates mask — the full-union
totals. SetSweepContext.build therefore checks the full-membership
worst case once, host-side in int64, before anything rides the int32
device path; the per-class capacity-cumsum bound is lane-independent
(removed slots only LOWER capacity), so the prefix sweep's bound carries
over unchanged.

**Search** (sweep_sets): bounded proposal->feasibility->reseed rounds
under the existing multi-node consolidation timeout. Round 0 proposes
every prefix (strictly subsuming the prefix sweep), per-nodepool
prefixes, and seeded random sets; later rounds are leave-one-out /
add-one / swap neighborhoods of the best known set plus fresh random
sets. Every round is ONE bounded device dispatch over up to
MAX_SET_LANES membership rows — no per-set host round-trips (the
ir-transfer budget pins this). The winner is materialized through the
real compute_consolidation path, so prices, spot-to-spot rules, and
replacement construction stay byte-identical to the sequential method;
feasible prefixes are walked largest-first as a backstop — the prefix
sweep's own materialization rule — so the returned command's savings
can never fall below the prefix search's.

**Gates**: the set kernel supports exactly the delta-state fast shape
(sweep.fast_gate_reason — bulk gates, no topology constraints or
inverse groups among union pods, one requirement class) on top of the
shared union gates (no nodepool limits, draining non-candidates,
missing views, host ports). Anything else raises SweepUnsupported and
MultiNodeConsolidation falls down the strategy ladder: sets -> batched
prefixes -> binary search -> sequential oracle probes
(docs/consolidation.md).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from karpenter_tpu import tracing
from karpenter_tpu.controllers.disruption.sweep import (
    SweepUnsupported,
    build_union,
    capacity_cumsum_fits_int32,
    fast_gate_reason,
)
from karpenter_tpu.controllers.disruption.types import Candidate, Command

# lane cap for one device dispatch; proposals beyond it queue for the
# next round rather than growing the compiled program unboundedly
MAX_SET_LANES = 4096
# proposal->feasibility->reseed rounds per sweep (each is one dispatch)
MAX_SET_ROUNDS = 6
# top-ranked non-prefix sets materialized through compute_consolidation
# (the prefix backstop walk rides separately); each materialization is
# one exact simulation
MATERIALIZE_TRIES = 6
# lane-count bucket floor: rounds of different sizes pad to the same
# compiled program (pow-2 buckets, tpu_problem._pow2)
LANE_BUCKET_FLOOR = 64

_set_sweep_cached = None

# bench/introspection: sweep_sets overwrites this with the last search's
# round/lane/materialization counters (bench.py --consolidation reports
# them next to the c4 prefix-sweep row)
last_search_stats: dict = {}


# graftlint: disable=dtype-overflow  (int64 worst-case guards live in SetSweepContext.build — max over masks == full membership; device math must stay int32)
def _set_sweep_kernel(
    tb, st, x, avail0, slot_cand, member, base_counts, percand_counts, sizes
):
    """The removal-set sweep: (feasible[B], odometer steps) for
    membership rows member
    [B, J] (int32 0/1). slot_cand [E] maps existing slots to candidate
    indices (J = not a candidate); percand_counts [J, C] is the
    per-candidate class-count matrix P; base_counts [C] counts pods
    valid in every lane (pending pods).

    The prefix kernel derives its lanes from the lane index
    (sweep._fast_sweep_kernel); here both the disabled-slot mask and the
    per-class valid-pod counts derive from M — a gather and a matmul —
    and the shared core does the rest. Exactness rides the same gates
    (fast_gate_reason) plus the caller's int64 guards."""
    import jax.numpy as jnp

    from karpenter_tpu.controllers.disruption.sweep import (
        _ffd_feasibility_core,
    )
    from karpenter_tpu.solver import tpu_runs as KR

    rc = KR._build_cache(tb, st, x)
    B = member.shape[0]
    # pad a zero column so the sentinel J gathers "never removed"
    member_pad = jnp.concatenate(
        [member, jnp.zeros((B, 1), member.dtype)], axis=1
    )
    removed = member_pad[:, slot_cand] > 0  # [B, E]
    counts = base_counts[None, :] + member @ percand_counts  # [B, C] i32
    avail = jnp.where(
        removed[..., None], jnp.int32(-1), avail0[None]
    )  # [B, E, R]
    return _ffd_feasibility_core(tb, rc, avail, counts, sizes)


class SetSweepContext:
    """Built ONCE per consolidation pass: the union problem, the device
    tables (uploaded once — CLAUDE.md: per-class tables ship once per
    solve), and the per-candidate class-count matrix. evaluate() then
    scores ANY batch of removal sets in one device dispatch; only the
    [B, J] membership mask crosses the tunnel per round."""

    def __init__(
        self,
        candidates: list[Candidate],
        sched,
        tb,
        base_st,
        x_row,
        avail0,
        slot_cand,
        base_counts,
        percand_counts,
        sizes,
        trivial: bool,
    ):
        self.candidates = candidates
        self.sched = sched
        self.tb = tb
        self.base_st = base_st
        self.x_row = x_row
        self.avail0 = avail0
        self.slot_cand = slot_cand
        self.base_counts = base_counts
        self.percand_counts = percand_counts
        self.sizes = sizes
        self.trivial = trivial  # no union pods: every set feasible
        self.n_candidates = len(candidates)
        # unknown prices ride as MAX_FLOAT (helpers.py _candidate_price);
        # rank them as 0 — unknown is not infinitely valuable, and inf
        # estimates would otherwise dominate every ranking they touch
        from karpenter_tpu.cloudprovider.types import MAX_FLOAT

        raw = np.array([c.price for c in candidates], np.float64)
        self.prices = np.where(raw >= MAX_FLOAT, 0.0, raw)

    @classmethod
    def build(
        cls, kube, cluster, cloud_provider, candidates, options=None,
        trace=None,
    ) -> "SetSweepContext":
        """Union gates + set-kernel gates + int64 guards + one table
        upload. Raises SweepUnsupported when the set kernel cannot
        express the shape (the controller falls down the ladder). The
        persistent compile cache is configured by the solver package
        import. `trace` collects the union encode/upload spans."""
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.solver.tpu_problem import (
            _pow2,
            contiguous_class_seq,
            group_class_counts,
        )

        if not candidates:
            raise SweepUnsupported("no candidates for set sweep")
        u = build_union(kube, cluster, cloud_provider, candidates, options,
                        trace=trace)
        p = u.problem
        reason = fast_gate_reason(p)
        if reason is not None:
            # unlike the prefix path there is no vmapped full-state
            # fallback for arbitrary sets — the lattice is too big to
            # carry full per-lane State; fall down the ladder instead
            raise SweepUnsupported(f"set sweep needs the fast shape: {reason}")

        J = len(candidates)
        order_arr = np.asarray(u.order, dtype=np.int64)
        ordered_cls = p.pod_class[order_arr]
        if len(ordered_cls) == 0:
            return cls(
                candidates, u.sched, u.tb, u.base, None, None, None, None,
                None, None, trivial=True,
            )
        class_seq = contiguous_class_seq(ordered_cls)
        if class_seq is None:
            raise SweepUnsupported(
                "encode classes not contiguous in FFD order (sig collision)"
            )
        pp = np.asarray(u.pod_prefix)[order_arr]
        base, P = group_class_counts(ordered_cls, class_seq, pp, J)
        sizes = p.prequests_c[class_seq].astype(np.int32)
        C = len(class_seq)

        # int64 guards (module docstring): counts are sums of
        # non-negative per-candidate contributions, so the ALL-candidates
        # mask dominates every membership row — check the full-union
        # worst case once. The capacity cumsum is lane-independent
        # (removed slots only lower it), so the shared base-availability
        # bound (sweep.capacity_cumsum_fits_int32) suffices for every
        # mask.
        full = base + P.sum(axis=0)  # [C] int64, M = all-ones row
        worst_tot = full @ sizes.astype(np.int64)
        if (worst_tot >= (1 << 30)).any():
            raise SweepUnsupported(
                "worst-case removal-set totals exceed int32"
            )
        if not capacity_cumsum_fits_int32(p.eavail, sizes):
            raise SweepUnsupported(
                "per-class capacity cumsum exceeds int32"
            )

        # J padded to a pow-2 bucket so nearby candidate counts share one
        # compiled program (padded candidates have zero P rows and no
        # slots, so their membership bits are inert)
        Jp = _pow2(J, floor=8)
        P_pad = np.zeros((Jp, C), np.int64)
        P_pad[:J] = P
        slot_cand = np.full(p.num_existing, Jp, np.int32)
        for j, c in enumerate(candidates):
            slot_cand[u.view_slot[c.name]] = j

        rep_i = p.class_reps[int(p.rclass_creps[0])]
        xs1 = u.sched._pod_xs(p, [rep_i])
        x_row = jax.tree_util.tree_map(lambda a: a[0], xs1)
        return cls(
            candidates,
            u.sched,
            u.tb,
            u.base,
            x_row,
            jnp.asarray(p.eavail),
            jnp.asarray(slot_cand),
            jnp.asarray(base.astype(np.int32)),
            jnp.asarray(P_pad.astype(np.int32)),
            jnp.asarray(sizes),
            trivial=False,
        )

    def evaluate(self, member: np.ndarray, trace=None) -> np.ndarray:
        """feasible[B] for a [B, J] boolean/0-1 membership batch — ONE
        bounded device dispatch (per-set host round-trips would defeat
        the design; the setsweep[runtime] ir-transfer budget pins the
        dispatch count). Lane counts pad to pow-2 buckets so every round
        size shares a compiled program. Each dispatch records a span on
        `trace` plus the dispatch/lane counters (sets-per-dispatch =
        karpenter_sweep_set_lanes_total / karpenter_solve_dispatches_total
        {path=setsweep})."""
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.solver.tpu_problem import _pow2

        member = np.asarray(member)
        if member.ndim != 2 or member.shape[1] != self.n_candidates:
            raise ValueError(
                f"member must be [B, {self.n_candidates}], got {member.shape}"
            )
        B = member.shape[0]
        if B == 0:
            return np.zeros(0, bool)
        if B > MAX_SET_LANES:
            raise SweepUnsupported(f"{B} set lanes > {MAX_SET_LANES}")
        if self.trivial:
            return np.ones(B, bool)
        Bp = _pow2(B, floor=LANE_BUCKET_FLOOR)
        Jp = int(self.percand_counts.shape[0])
        padded = np.zeros((Bp, Jp), np.int32)
        padded[:B, : self.n_candidates] = member.astype(np.int32)
        with tracing.span_of(
            trace, "dispatch", path="setsweep", lanes=B
        ) as dsp:
            out, odo_steps = self._dispatch(jnp.asarray(padded))
            out, odo_steps = jax.device_get((out, odo_steps))
            feas = np.asarray(out)[:B].astype(bool)
            dsp["kernel"] = {"steps": int(odo_steps), "lanes": B}
        if trace is not None:
            trace.count("dispatches")
            trace.count("set_lanes", by=B)
            trace.count("kernel_iterations", by=int(odo_steps))
        tracing.SOLVE_DISPATCHES.inc({"path": "setsweep"})
        tracing.SWEEP_SET_LANES.inc(by=B)
        tracing.KERNEL_ITERATIONS.inc({"path": "setsweep"}, by=int(odo_steps))
        return feas

    def _dispatch(self, member_dev):
        """The single jitted call per proposal round (counted by the
        ir-transfer budget)."""
        import jax

        global _set_sweep_cached
        if _set_sweep_cached is None:
            _set_sweep_cached = jax.jit(_set_sweep_kernel)
        return _set_sweep_cached(
            self.tb,
            self.base_st,
            self.x_row,
            self.avail0,
            self.slot_cand,
            member_dev,
            self.base_counts,
            self.percand_counts,
            self.sizes,
        )

    def savings_estimate(self, member: np.ndarray) -> np.ndarray:
        """[B] — Σ removed candidate prices per lane: the materialization
        ranking key (an upper bound on real savings; compute_consolidation
        subtracts the replacement's price exactly)."""
        return np.asarray(member, np.float64) @ self.prices


class SetProposer:
    """Bounded removal-set proposal generator. Round 0 strictly subsumes
    the prefix sweep (every prefix is a lane) and adds per-nodepool
    prefixes plus seeded random sets; reseed rounds explore
    leave-one-out / add-one / swap neighborhoods of the best known set.
    Deduplicates across rounds so the search never re-dispatches a
    scored set."""

    def __init__(
        self, candidates: list[Candidate], seed: int = 0,
        max_lanes: int = MAX_SET_LANES,
    ):
        self.J = len(candidates)
        self.pools = [c.nodepool_name for c in candidates]
        self.rng = np.random.default_rng(seed)
        self.max_lanes = max_lanes
        self._seen: set[bytes] = set()

    def _dedup(self, rows: np.ndarray) -> np.ndarray:
        out: list[np.ndarray] = []
        for r in np.asarray(rows, bool).reshape(-1, self.J):
            if not r.any():
                continue  # the empty set is a no-op by definition
            key = np.packbits(r).tobytes()
            if key in self._seen:
                continue
            self._seen.add(key)
            out.append(r)
            if len(out) >= self.max_lanes:
                break
        return np.asarray(out, bool).reshape(len(out), self.J)

    def _random(self, n: int) -> np.ndarray:
        # densities spread over (0, 1): small sets and near-full sets
        # both get sampled
        dens = self.rng.uniform(0.1, 0.9, size=(n, 1))
        return self.rng.random((n, self.J)) < dens

    def first_round(self) -> np.ndarray:
        J = self.J
        rows = [np.tril(np.ones((J, J), bool))]  # lane k = candidates[:k+1]
        for pool in sorted(set(self.pools)):
            idx = [j for j, pl in enumerate(self.pools) if pl == pool]
            m = np.zeros((len(idx), J), bool)
            for k in range(len(idx)):
                m[k, idx[: k + 1]] = True
            rows.append(m)
        rows.append(self._random(max(2 * J, 16)))
        return self._dedup(np.concatenate(rows, axis=0))

    def neighborhood(self, best: np.ndarray) -> np.ndarray:
        """Local moves around the best known set, plus fresh random
        sets so the search never stalls in a one-move basin."""
        best = np.asarray(best, bool)
        rows: list[np.ndarray] = []
        members = np.flatnonzero(best)
        outside = np.flatnonzero(~best)
        for j in members:  # leave-one-out
            r = best.copy()
            r[j] = False
            rows.append(r)
        for j in outside:  # add-one
            r = best.copy()
            r[j] = True
            rows.append(r)
        if len(members) and len(outside):  # swaps (sampled)
            for _ in range(min(64, len(members) * len(outside))):
                r = best.copy()
                r[self.rng.choice(members)] = False
                r[self.rng.choice(outside)] = True
                rows.append(r)
        rows.append(self._random(max(self.J, 8)))
        return self._dedup(
            np.concatenate([np.atleast_2d(r) for r in rows], axis=0)
        )


def _prefix_len(mask: np.ndarray) -> int:
    """k if mask is exactly candidates[:k], else 0."""
    k = int(mask.sum())
    return k if k and bool(mask[:k].all()) else 0


def sweep_sets(consolidation, candidates: list[Candidate]) -> Command:
    """MultiNodeConsolidation's sweep="sets" search: bounded
    proposal->batched-feasibility->reseed rounds under the multi-node
    timeout, then materialize the winners through the real
    compute_consolidation path (feasible prefixes are walked
    largest-first as a backstop — the prefix sweep's own rule — so the
    result's savings are >= the prefix search's on every supported
    shape). Raises SweepUnsupported when the set kernel cannot express
    the problem."""
    from karpenter_tpu.controllers.disruption.types import command_savings

    tr = tracing.new_trace("setsweep")
    tr.annotate(candidates=len(candidates))
    try:
        cmd = _sweep_sets_traced(consolidation, candidates, command_savings, tr)
    except SweepUnsupported:
        # ladder control flow (the controller falls to the prefix rung);
        # finish keeps unsupported traces out of the /debug/solves ring
        tr.finish("unsupported")
        raise
    except BaseException:
        tr.finish("error")
        raise
    tr.finish("ok")
    return cmd


def _sweep_sets_traced(
    consolidation, candidates: list[Candidate], command_savings, tr
) -> Command:
    ctx = SetSweepContext.build(
        consolidation.kube,
        consolidation.cluster,
        consolidation.cloud,
        candidates,
        consolidation.opts,
        trace=tr,
    )
    clock = consolidation.clock
    deadline = (
        clock.now()
        + consolidation.opts.multinode_consolidation_timeout_seconds
    )
    proposer = SetProposer(candidates, seed=len(candidates))
    feasible_masks: list[np.ndarray] = []
    best_mask: Optional[np.ndarray] = None
    best_est = -1.0
    with tr.span("propose"):
        batch = proposer.first_round()
    rounds = 0
    lanes = 0
    while len(batch) and rounds < MAX_SET_ROUNDS and clock.now() <= deadline:
        feas = ctx.evaluate(batch, trace=tr)
        rounds += 1
        lanes += len(batch)
        ests = ctx.savings_estimate(batch)
        improved = False
        for r, ok, est in zip(batch, feas, ests):
            if not ok:
                continue
            feasible_masks.append(r)
            if est > best_est + 1e-12:
                best_mask, best_est = r, float(est)
                improved = True
        if not improved or best_mask is None:
            break
        with tr.span("propose"):
            batch = proposer.neighborhood(best_mask)

    # ---- materialize -----------------------------------------------------
    # Kernel feasibility is SCHEDULABILITY; compute_consolidation also
    # applies the price and spot-to-spot rules, so a feasible set can
    # still materialize to a no-op (e.g. all-spot candidates whose
    # replacement would be spot with the gate off). Two passes:
    best_cmd = Command(reason=consolidation.reason)
    best_savings = 0.0

    # 1) prefix backstop — walk feasible prefix lengths largest-first
    #    until one materializes, exactly the prefix sweep's rule
    #    (sweep.sweep_first_n), so the returned command can never save
    #    less than the prefix search's
    feasible_ks = sorted(
        {k for k in (_prefix_len(r) for r in feasible_masks) if k},
        reverse=True,
    )
    for k in feasible_ks:
        with tr.span("materialize", prefix=k):
            cmd = consolidation.compute_consolidation(candidates[:k])
        if cmd.candidates:
            best_cmd, best_savings = cmd, command_savings(cmd)
            break

    # 2) top non-prefix sets by estimated savings (price sum, an upper
    #    bound that ignores replacement cost), ties toward larger sets;
    #    prefixes are pass 1's business and must not crowd the slice
    ranked = sorted(
        (r for r in feasible_masks if not _prefix_len(r)),
        key=lambda r: (-float(ctx.savings_estimate(r[None])[0]), -int(r.sum())),
    )
    for r in ranked[:MATERIALIZE_TRIES]:
        if clock.now() > deadline and best_cmd.candidates:
            break
        subset = [c for j, c in enumerate(candidates) if r[j]]
        with tr.span("materialize", set_size=len(subset)):
            cmd = consolidation.compute_consolidation(subset)
        if not cmd.candidates:
            continue
        s = command_savings(cmd)
        if s > best_savings + 1e-12 or (
            abs(s - best_savings) <= 1e-12
            and len(cmd.candidates) > len(best_cmd.candidates)
        ):
            best_cmd, best_savings = cmd, s

    last_search_stats.clear()
    last_search_stats.update(
        rounds=rounds,
        lanes_evaluated=lanes,
        feasible_sets=len(feasible_masks),
        winner_nodes=len(best_cmd.candidates),
        winner_savings_per_hour=best_savings,
    )
    tr.annotate(**last_search_stats)
    return best_cmd


# ---------------------------------------------------------------------------
# benchmark harness (bench.py --consolidation)


def bench_set_sweep(
    n_nodes: int = 2000, n_candidates: int = 100, lanes: int = 1024
) -> dict:
    """The bounded-dispatch demonstration at the c4 bench shape: >= 1000
    removal sets over a 2k-node fleet's top candidates evaluated in ONE
    device invocation, plus the full sweep_sets search vs the best-prefix
    strategies it subsumes."""
    from karpenter_tpu.controllers.disruption.consolidation import (
        MultiNodeConsolidation,
    )
    from karpenter_tpu.controllers.disruption.types import command_savings
    from karpenter_tpu.testing import fixtures

    op = fixtures.underutilized_operator(
        n_nodes, seed=7, force_oracle=False, max_ticks=400
    )

    args = (op.kube, op.cluster, op.cloud, op.clock)
    mnc = MultiNodeConsolidation(*args, options=op.opts, force_oracle=True)
    candidates = mnc.candidates()[:n_candidates]

    # one bounded dispatch over `lanes` sets: warm (compile) then steady
    ctx = SetSweepContext.build(op.kube, op.cluster, op.cloud, candidates, op.opts)
    proposer = SetProposer(candidates, seed=7, max_lanes=lanes)
    member = proposer.first_round()
    if len(member) < lanes:
        extra = proposer._dedup(proposer._random(4 * lanes))
        member = np.concatenate([member, extra], axis=0)[:lanes]
    t0 = time.monotonic()
    feas = ctx.evaluate(member)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    feas = ctx.evaluate(member)
    eval_s = time.monotonic() - t0

    # the full search vs the prefix strategies it subsumes
    mnc_sets = MultiNodeConsolidation(
        *args, sweep="sets", options=op.opts, force_oracle=False
    )
    t0 = time.monotonic()
    cmd_sets = mnc_sets.first_n_sets(candidates)
    sets_s = time.monotonic() - t0
    search_stats = dict(last_search_stats)
    mnc_prefix = MultiNodeConsolidation(
        *args, sweep="batched", options=op.opts, force_oracle=False
    )
    t0 = time.monotonic()
    cmd_prefix = mnc_prefix.first_n_batched(candidates)
    prefix_s = time.monotonic() - t0

    s_sets = command_savings(cmd_sets)
    s_prefix = command_savings(cmd_prefix)
    return {
        "nodes": n_nodes,
        "candidates": len(candidates),
        "sets_per_dispatch": int(len(member)),
        "dispatch_seconds": round(eval_s, 3),
        "dispatch_compile_seconds": round(max(0.0, compile_s - eval_s), 1),
        "sets_per_second": round(len(member) / eval_s, 1) if eval_s else None,
        "feasible_sets": int(np.asarray(feas).sum()),
        "search_rounds": search_stats.get("rounds"),
        "search_lanes_evaluated": search_stats.get("lanes_evaluated"),
        "search_feasible_sets": search_stats.get("feasible_sets"),
        "search_seconds": round(sets_s, 3),
        "prefix_search_seconds": round(prefix_s, 3),
        "sets_savings_per_hour": round(s_sets, 4),
        "best_prefix_savings_per_hour": round(s_prefix, 4),
        "savings_ratio": round(s_sets / s_prefix, 3) if s_prefix else None,
        "sets_command_nodes": len(cmd_sets.candidates),
        "prefix_command_nodes": len(cmd_prefix.candidates),
        "winner_is_prefix": bool(
            _prefix_len(
                np.isin(
                    [c.name for c in candidates],
                    [c.name for c in cmd_sets.candidates],
                )
            )
        ),
    }
