"""Batched multi-node consolidation: evaluate every candidate-prefix
removal set in ONE device invocation.

The reference's multi-node consolidation binary-searches prefixes of the
cost-sorted candidate list, running a full scheduling simulation per probe
(/root/reference/pkg/controllers/disruption/multinodeconsolidation.go:116
firstNConsolidationOption — ~log2(N) sequential simulations). This module
replaces the search with a tensor sweep: the prefix index becomes a batch
axis and a vmapped scan kernel (solver/tpu_kernel.solve_scan) solves all
prefixes simultaneously — the "thousands of candidate removal sets in
parallel" capability the TPU buys (BASELINE.json north star).

Construction: one tensor problem holds every candidate node as an existing
slot plus the union of all candidates' reschedulable pods in FFD order.
Per prefix k:
- candidate slots [0, k) are disabled (available = -1 never fits);
- pods bound to candidates [k, N) stay bound: their topology-count
  contributions are restored via per-candidate count deltas (prefix sums);
- only the pods of candidates [0, k) are valid in the scan.
A prefix is consolidation-feasible when every valid pod schedules and at
most one new claim is opened (consolidation.go:184 multi-node replacements
are never a win). The host then materializes the final Command for the
largest feasible prefix through the real compute_consolidation path, so
prices, spot rules, and replacement construction are byte-identical to the
sequential method.

Gates (fall back to the sequential prefix scan when violated): nodepool
limits, reserved capacity — anything where per-prefix state diverges
beyond availability and topology counts.

Two device strategies live here:

1. **The delta-state fast path** (_fast_sweep_kernel, round 4) — the
   dedicated batched kernel round 3's measurements called for. Under the
   bulk gates (no minValues/limits/reservations, no topology ownership or
   inverse selection among the union pods, one requirement class), FFD of
   a class-grouped pod sequence is not a sequential scan: pods of a class
   are identical, so first-fit over the ordered node list is one masked
   cumsum per class, and per-prefix state is just the candidate-disable
   mask plus evolving [B, E, R] availability. The whole 100-prefix sweep
   is ~C (≈ classes) scan steps in ONE device invocation.
2. **The vmapped full-state scan** — exact for every encodable shape, used
   when the fast gates fail on small problems; large non-gated problems
   fall back to the binary search instead (the vmap carries full per-lane
   State and executes all branches, measured 39s at 2k nodes in round 3).

Measured round 4 (BENCH_DETAIL.json c4, 2k nodes x 100 prefixes, real
chip): fast sweep 1.54s steady vs oracle binary search 2.08s — the sweep
WINS (1.35x) and all strategies agree on the largest feasible prefix
(agree=true). TPU-probe binary: 1.96s. "batched" is now the default
strategy (consolidation.py), falling back to binary on SweepUnsupported.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import numpy as np

from karpenter_tpu import tracing
from karpenter_tpu.api import labels as well_known
from karpenter_tpu.controllers.disruption.types import Candidate
from karpenter_tpu.controllers.state import cluster_source, is_reschedulable
from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver, encode_problem

MAX_SWEEP_PREFIXES = 128


class SweepUnsupported(Exception):
    """Problem shape outside the batched sweep; use the sequential scan."""


_fast_sweep_cached = None


# graftlint: disable=dtype-overflow  (int64 worst-case guards live in the callers — _fast_prefix_feasibility and setsweep.SetSweepContext.build; device math must stay int32)
def _ffd_feasibility_core(tb, rc, avail, counts, sizes):
    """Shared device body of every delta-state sweep kernel: given
    per-lane availability `avail` [B, E, R] (-1 marks a removed slot) and
    per-lane valid-pod counts `counts` [B, C] over the contiguous class
    sequence (sizes [C, R]), run the class-cumsum FFD identity and the
    <=1-new-claim check, returning (feasible [B], steps i32) — `steps`
    is the kernel-odometer count of class-scan body trips (the device
    loop iterations this dispatch executed; write-only, so the
    feasibility verdicts are byte-identical with it carried).

    How the lanes were derived is the caller's business: the prefix
    kernel below compares candidate indices against the lane index, the
    removal-set kernel (setsweep.py _set_sweep_kernel) gathers a
    membership bitmask — both collapse to the same [B, E, R] / [B, C]
    interface, so this core is the single exactness surface the parity
    matrices pin."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.solver import tpu_kernel as K

    B, C = counts.shape
    INF = jnp.int32(1 << 30)
    ok_e = rc.ok_e  # [E] — static screen, same for every class (one rclass)

    def body(carry, c):
        avail, steps = carry
        s = sizes[c]  # [R]
        per = jnp.where(
            (s > 0)[None, None, :], avail // jnp.maximum(s, 1)[None, None, :], INF
        )
        cap = jnp.min(per, axis=-1)
        cap = jnp.where(jnp.all(avail >= 0, axis=-1), jnp.maximum(cap, 0), 0)
        cap = jnp.where(ok_e[None, :], cap, 0)  # [B, E] pod-units per node
        csum = jnp.cumsum(cap, axis=1)
        before = csum - cap
        take = jnp.clip(counts[:, c][:, None] - before, 0, cap)
        avail = avail - take[..., None] * s[None, None, :]
        left_c = counts[:, c] - take.sum(axis=1)
        return (avail, steps + 1), left_c

    (avail, steps), leftT = jax.lax.scan(
        body, (avail, jnp.zeros((), jnp.int32)), jnp.arange(C)
    )
    left = leftT.T  # [B, C] — pods that fit no existing node
    tot = (left[:, :, None] * sizes[None]).sum(axis=1)  # [B, R]
    any_left = left.sum(axis=1) > 0

    # <=1 new claim: the first leftover pod opens a claim on the FIRST
    # template that can host it (scheduler.go:587 template order); all
    # remaining leftovers must then fit that same claim — one type must
    # accommodate the full leftover total plus daemon overhead.
    I = tb.ialloc.shape[0]
    tmember = jax.vmap(lambda w: K._unpack(w, I))(tb.ttypes)  # [T, I]

    def t_fit(final_row, member, totals):
        return jnp.any(K._type_filter(final_row, member, totals, tb))

    fit1 = jax.vmap(
        lambda f, m, d: jax.vmap(lambda s_: t_fit(K.Reqs(*f), m, d + s_))(sizes)
    )(tuple(rc.final_t), tmember, tb.tdaemon)  # [T, C]
    cand_t = rc.ok_t[:, None] & fit1  # [T, C]
    c0 = jnp.argmax(left > 0, axis=1)  # first leftover class per lane
    ct = cand_t[:, c0]  # [T, B]
    has_t = jnp.any(ct, axis=0)
    tstar = jnp.argmax(ct, axis=0)  # [B]
    fit_tot = jax.vmap(
        lambda t, tot_b: t_fit(
            K._row(rc.final_t, t), tmember[t], tb.tdaemon[t] + tot_b
        )
    )(tstar, tot)
    claim_ok = has_t & fit_tot
    return jnp.where(any_left, claim_ok, True), steps


# graftlint: disable=dtype-overflow  (int64 worst-case guards live in the caller, _fast_prefix_feasibility; device math must stay int32)
def _fast_sweep_kernel(tb, st, x, avail0, cand_idx, counts, sizes, singleton=False):
    """The delta-state consolidation sweep (module docstring §fast path).

    Key identity: FFD of a CLASS-GROUPED pod sequence with capacity-only
    constraints is not a sequential per-pod scan — pods of one class are
    identical, so first-fit over the ordered node list means "node e takes
    min(remaining, cap_e)" where cap_e is the node's pod-unit capacity:
    one masked cumsum per class. The whole 100-prefix sweep is then C
    (≈ number of classes) scan steps over [B, E] tensors instead of
    ~|pods| while-loop iterations per vmap lane carrying full State.

    Exactness relies on the caller's gates: bulk gates hold (pairwise type
    screens exact, offerings decompose, no minValues/limits), no union pod
    owns or is inversely selected by any topology constraint, and all
    union pods share one requirement class (so the static screens ok_e /
    ok_t / final_t from the run kernel's _build_cache apply to every
    class, and a single open claim stays compatible with every leftover
    pod — scheduler.go:488's existing→claim→new order reduces to
    "leftovers after existing nodes must fit the first workable template").

    Returns (feasible [B], odometer steps) — see _ffd_feasibility_core.
    """
    import jax.numpy as jnp

    from karpenter_tpu.solver import tpu_runs as KR

    rc = KR._build_cache(tb, st, x)
    B = counts.shape[0]
    karr = jnp.arange(B, dtype=jnp.int32)
    # per-lane availability: removed candidate slots fit nothing (-1).
    # prefix mode: lane k removes candidates[:k+1]; singleton mode
    # (single-node consolidation, round 5): lane k removes ONLY
    # candidates[k] — the lanes are fully independent simulations
    removed = (
        cand_idx[None, :] == karr[:, None]
        if singleton
        else cand_idx[None, :] <= karr[:, None]
    )
    avail = jnp.where(
        removed[..., None],
        jnp.int32(-1),
        avail0[None],
    )  # [B, E, R]
    return _ffd_feasibility_core(tb, rc, avail, counts, sizes)


def capacity_cumsum_fits_int32(eavail, sizes) -> bool:
    """Host-side int64 proof that the delta-state kernels' per-class
    capacity cumsum cannot wrap int32. The worst case is the BASE
    availability divided by the class size — removed slots only LOWER
    availability, so the bound is lane-independent and shared by every
    sweep scheme (prefix, singleton, arbitrary membership sets); one
    copy here keeps the guard in lockstep with _ffd_feasibility_core's
    cap derivation for both callers."""
    avail64 = np.asarray(eavail).astype(np.int64)
    ok_rows = (avail64 >= 0).all(axis=1)
    for c in range(len(sizes)):
        s = np.asarray(sizes[c]).astype(np.int64)
        per = np.where(s > 0, avail64 // np.maximum(s, 1), 1 << 30)
        cap0 = np.where(ok_rows, np.maximum(per.min(axis=1), 0), 0)
        if int(cap0.sum()) >= (1 << 31):
            return False
    return True


def fast_gate_reason(problem) -> Optional[str]:
    """Why the delta-state fast shape does NOT apply to this union
    problem (None = it does). Shared with the removal-set subsystem
    (setsweep.py), which supports EXACTLY this shape: the prefix path
    falls back to its vmapped full-state scan on a reason, the set path
    raises SweepUnsupported with it."""
    from karpenter_tpu.solver.tpu import _bulk_gates

    p = problem
    if not _bulk_gates(p):
        return "bulk gates fail (minValues/limits/daemon host ports/type structure)"
    if (p.ptopo_kind_c != 0).any() or p.pinv_h_c.any() or p.pown_h_c.any():
        return "topology constraints among union pods"
    if any(hg.inverse for hg in p.hgroups):
        return "inverse hostname groups (anti-affinity) in union problem"
    if len(p.rclass_creps) != 1:
        return "union pods span multiple requirement classes"
    return None


def _fast_prefix_feasibility(
    sched, problem, candidates, view_slot, order, pod_prefix, tb, base_st,
    singleton=False, trace=None,
):
    """Gate-check + run the delta-state sweep kernel; None = gates failed,
    caller falls back to the vmapped full-state sweep. tb/base_st come
    from the caller — _tables re-uploads the full device table set over
    the tunnel, so it must run once per sweep (CLAUDE.md: upload per-class
    tables once per solve)."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.solver import tpu_kernel as K
    from karpenter_tpu.solver.tpu_problem import (
        contiguous_class_seq,
        group_class_counts,
    )

    p = problem
    if fast_gate_reason(p) is not None:
        return None

    cls = p.pod_class
    order_arr = np.asarray(order, dtype=np.int64)
    ordered_cls = cls[order_arr]
    if len(ordered_cls) == 0:
        return [True] * len(candidates)
    class_seq = contiguous_class_seq(ordered_cls)
    if class_seq is None:
        return None  # classes not contiguous in FFD order (sig collision)

    C = len(class_seq)
    B = len(candidates)
    pp = np.asarray(pod_prefix)[order_arr]
    base, M = group_class_counts(ordered_cls, class_seq, pp, B)
    # prefix lanes accumulate candidates[:k+1]'s pods; singleton lanes
    # carry only candidate k's
    counts = (
        (M + base[None]) if singleton else (np.cumsum(M, axis=0) + base[None])
    ).astype(np.int32)
    sizes = p.prequests_c[class_seq].astype(np.int32)
    cand_idx = np.full(p.num_existing, (1 << 30), np.int32)
    for j, c in enumerate(candidates):
        cand_idx[view_slot[c.name]] = j

    # int32-exactness guards (host-side, int64): the kernel sums
    # left*sizes and cumsums per-node pod-unit capacities in int32 —
    # feasibility verdicts must never ride a wrapped total. Worst-case
    # leftover total is every union pod left over; worst-case capacity
    # cumsum is the base availability divided by the class size.
    worst_tot = counts.max(axis=0).astype(np.int64) @ sizes.astype(np.int64)
    if (worst_tot >= (1 << 30)).any():
        return None
    if not capacity_cumsum_fits_int32(p.eavail, sizes):
        return None

    rep_i = problem.class_reps[int(problem.rclass_creps[0])]
    xs1 = sched._pod_xs(problem, [rep_i])
    x_row = jax.tree_util.tree_map(lambda a: a[0], xs1)

    global _fast_sweep_cached
    if _fast_sweep_cached is None:
        _fast_sweep_cached = jax.jit(
            _fast_sweep_kernel, static_argnames=("singleton",)
        )
    with tracing.span_of(
        trace, "dispatch", path="sweep_fast", lanes=len(candidates)
    ) as dsp:
        feasible, odo_steps = _fast_sweep_cached(
            tb,
            base_st,
            x_row,
            jnp.asarray(p.eavail),
            jnp.asarray(cand_idx),
            jnp.asarray(counts),
            jnp.asarray(sizes),
            singleton=singleton,
        )
        feasible, odo_steps = jax.device_get((feasible, odo_steps))
        dsp["kernel"] = {"steps": int(odo_steps)}
        tracing.KERNEL_ITERATIONS.inc({"path": "sweep"}, by=int(odo_steps))
        if trace is not None:
            trace.count("kernel_iterations", by=int(odo_steps))
        return [bool(v) for v in np.asarray(feasible)]


class UnionSweep:
    """One union problem shared by every batched removal scheme: all
    candidate nodes stay existing slots, all candidates' reschedulable
    pods (plus pending pods) are solve pods, tables uploaded once.
    Built by build_union; consumed by prefix_feasibility here and by
    setsweep.SetSweepContext."""

    __slots__ = (
        "sched", "problem", "pods", "pod_prefix", "order", "view_slot",
        "tb", "base",
    )

    def __init__(self, sched, problem, pods, pod_prefix, order, view_slot,
                 tb, base):
        self.sched = sched
        self.problem = problem
        self.pods = pods
        self.pod_prefix = pod_prefix
        self.order = order
        self.view_slot = view_slot
        self.tb = tb
        self.base = base


def build_union(
    kube, cluster, cloud_provider, candidates: list[Candidate], options=None,
    trace=None,
) -> UnionSweep:
    """Shared front half of every batched sweep: the union gates
    (nodepool limits, draining non-candidates, missing views, host
    ports), the union problem encode, the shared FFD order, and the
    one-per-sweep device table upload. Raises SweepUnsupported on any
    gate; the caller picks the lane semantics (prefix / singleton /
    arbitrary membership sets). The persistent compile cache is
    configured by the solver package import. `trace` (tracing.Trace)
    collects the encode/order/upload phase spans when the caller rides
    a sweep trace."""
    node_pools = [np_ for np_ in kube.list("NodePool") if np_.replicas is None]
    if any(np_.limits for np_ in node_pools):
        raise SweepUnsupported("nodepool limits make per-prefix state diverge")
    # pods draining off OTHER deleting nodes are part of every sequential
    # simulation (helpers.py:69-73); their per-prefix handling isn't modeled
    # here, so bail to the sequential scan when any exist
    candidate_names = {c.name for c in candidates}
    for sn in cluster.state_nodes():
        if sn.name in candidate_names:
            continue
        if sn.marked_for_deletion or sn.deleting():
            if any(is_reschedulable(pd) for pd in cluster.pods_on(sn.name)):
                raise SweepUnsupported(
                    "reschedulable pods draining off non-candidate nodes"
                )
    its_by_pool = {
        np_.name: cloud_provider.get_instance_types(np_) for np_ in node_pools
    }
    daemonset_pods = [ds.pod_template for ds in kube.list("DaemonSet")]

    # union problem: every candidate node stays an existing slot; every
    # candidate's reschedulable pods join the pod list
    views = list(cluster.schedulable_node_views())
    view_slot = {v.name: e for e, v in enumerate(views)}
    missing = [c.name for c in candidates if c.name not in view_slot]
    if missing:
        raise SweepUnsupported(f"candidates missing from schedulable views: {missing}")

    pods = []
    pod_prefix = []  # pod i becomes valid from prefix index pod_prefix[i]
    for j, c in enumerate(candidates):
        for pod in c.reschedulable_pods:
            pods.append(pod.deep_copy())
            pod_prefix.append(j)
    pending = kube.pending_pods()
    for pod in pending:
        pods.append(pod.deep_copy())
        pod_prefix.append(-1)  # valid in every prefix

    # full-cluster topology (all nodes, all bound pods)
    topology = Topology(
        node_pools,
        its_by_pool,
        pods,
        cluster=cluster_source(kube, cluster),
        state_node_views=views,
    )
    sched = TpuScheduler(
        node_pools,
        its_by_pool,
        topology,
        views,
        daemonset_pods,
        SchedulerOptions(
            timeout_seconds=getattr(options, "solve_timeout_seconds", None)
        ),
    )
    try:
        with tracing.span_of(trace, "encode", pods=len(pods)):
            problem = encode_problem(sched.oracle, pods)
    except UnsupportedBySolver as e:
        raise SweepUnsupported(str(e)) from e
    if problem.num_host_ports:
        # per-lane host-port usage deltas (ports freed by removed
        # candidates) aren't modeled in the batched construction; the
        # sequential scans handle them exactly
        raise SweepUnsupported("host ports in sweep problem")

    # FFD order shared with the oracle
    from karpenter_tpu.solver.ordering import ffd_sort_key

    data = sched.oracle.cached_pod_data
    for pod in pods:
        sched.oracle._update_cached_pod_data(pod)
    order = sorted(
        range(len(pods)),
        key=lambda i: ffd_sort_key(pods[i], data[pods[i].uid].requests),
    )

    with tracing.span_of(trace, "upload"):
        tb = sched._tables(problem)  # also sets sched._typeok
        sched._upload_pod_tables(problem)
    # a consolidation-feasible removal set opens at most 1 new claim; a
    # set that overflows even a handful of slots is infeasible anyway
    N = 8
    base = sched._init_state(problem, N)
    return UnionSweep(
        sched, problem, pods, pod_prefix, order, view_slot, tb, base
    )


def prefix_feasibility(
    kube,
    cluster,
    cloud_provider,
    candidates: list[Candidate],
    options=None,
    singleton: bool = False,
    trace=None,
) -> list[bool]:
    """[len(candidates)] — feasible(k), all lanes evaluated in one device
    call. Prefix mode (multi-node consolidation): lane k removes
    candidates[:k+1]. Singleton mode (single-node consolidation, round
    5): lane k removes ONLY candidates[k] — the same machinery with
    per-candidate instead of cumulative deltas (singlenodeconsolidation
    .go:56 loops these simulations sequentially; here they are
    independent device lanes)."""
    tr = trace if trace is not None else tracing.new_trace("sweep")
    tr.annotate(candidates=len(candidates), singleton=singleton)
    try:
        out = _prefix_feasibility_traced(
            kube, cluster, cloud_provider, candidates, options, singleton, tr
        )
    except SweepUnsupported:
        # expected ladder control flow, not a failure: the controller
        # falls to the next strategy rung (finish keeps these out of
        # the ring)
        if trace is None:
            tr.finish("unsupported")
        raise
    except BaseException:
        if trace is None:
            tr.finish("error")
        raise
    if trace is None:
        tr.finish("ok")
    return out


def _prefix_feasibility_traced(
    kube, cluster, cloud_provider, candidates, options, singleton, tr
) -> list[bool]:
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.solver import tpu_kernel as K

    B = len(candidates)
    if B == 0:
        return []
    if B > MAX_SWEEP_PREFIXES:
        raise SweepUnsupported(f"{B} prefixes > {MAX_SWEEP_PREFIXES}")

    u = build_union(kube, cluster, cloud_provider, candidates, options,
                    trace=tr)
    sched, problem, pods = u.sched, u.problem, u.pods
    pod_prefix, order, view_slot = u.pod_prefix, u.order, u.view_slot
    tb, base = u.tb, u.base

    # delta-state fast path: under the bulk gates the whole sweep is C
    # cumsum steps on device (see _fast_sweep_kernel); the vmapped
    # full-state scan below remains the exact fallback for everything else
    # (the dispatch span lives INSIDE _fast_prefix_feasibility, around the
    # kernel call only — a declined gate check is not a device dispatch)
    fast = _fast_prefix_feasibility(
        sched, problem, candidates, view_slot, order, pod_prefix, tb,
        base, singleton=singleton, trace=tr,
    )
    if fast is not None:
        tr.count("dispatches")
        tracing.SOLVE_DISPATCHES.inc({"path": "sweep"})
        return fast
    # fast gates failed: the vmapped full-state scan below is exact but
    # carries B x full State (measured 39s at 2k nodes round 3) — on big
    # problems the sequential binary search is the better fallback
    if len(candidates) * len(pods) > 4096:
        raise SweepUnsupported(
            "delta-state gates failed on a large problem; binary search wins"
        )

    # ---- per-candidate topology deltas ----------------------------------
    # The base topology excluded every union pod from its counts (they're
    # solve pods, topology.py excluded_pods), so the base reflects "every
    # candidate removed". Per prefix k:
    #   + add back the reschedulable-pod counts of KEPT candidates (j > k)
    #   - remove the non-reschedulable-pod counts of REMOVED candidates
    #     (their daemonset riders vanish with the node, helpers.go:52)
    # replicating topology.py _count_domains (topology.go:328) per pod.
    from karpenter_tpu.scheduling import Requirements
    from karpenter_tpu.solver.tpu_problem import TERMINAL_PHASES

    slot_of = [view_slot[c.name] for c in candidates]
    Gv = base.v_cnt.shape[0]
    VMAX = base.v_cnt.shape[1]
    Gh = base.h_cnt.shape[0]
    S = base.h_cnt.shape[1]
    # deltas accumulate in int64; the guard below proves the restored
    # counts fit int32 before they ride the device state (CLAUDE.md:
    # int32 totals must never wrap)
    add_v = np.zeros((B, Gv, VMAX), np.int64)
    rm_v = np.zeros((B, Gv, VMAX), np.int64)
    add_h = np.zeros((B, Gh, S), np.int64)
    rm_h = np.zeros((B, Gh, S), np.int64)
    vocab = problem.vocab
    union_uids = {p.uid for p in pods}
    for j, c in enumerate(candidates):
        sn = cluster.node_by_name(c.name)
        node = sn.node if sn is not None else None
        labels = dict(node.metadata.labels) if node is not None else {}
        taints = list(node.taints) if node is not None else []
        label_reqs = Requirements.from_labels(labels)
        for pod in cluster.pods_on(c.name):
            if pod.phase in TERMINAL_PHASES or pod.terminating:
                continue
            resched = pod.uid in union_uids
            if pod.pod_anti_affinity:
                # anti-affinity pods on candidates create inverse hostname
                # groups whose per-prefix counts this construction doesn't
                # restore — bail to the sequential scan
                raise SweepUnsupported("anti-affinity pod on candidate")
            for g, vg in enumerate(problem.vgroups):
                tg = vg.group
                if pod.namespace not in tg.namespaces:
                    continue
                if tg.selector is None or not tg.selector.matches(
                    pod.metadata.labels
                ):
                    continue
                dom = labels.get(tg.key)
                if dom is None:
                    continue
                if not tg.node_filter.matches(taints, label_reqs):
                    continue
                vid = vocab.value_index[vg.kid].get(dom)
                if vid is None:
                    continue
                (add_v if resched else rm_v)[j, g, vid] += 1
            for g, hg in enumerate(problem.hgroups):
                if hg.inverse:
                    continue  # gated above
                tg = hg.group
                if pod.namespace not in tg.namespaces:
                    continue
                if tg.selector is None or not tg.selector.matches(
                    pod.metadata.labels
                ):
                    continue
                if not tg.node_filter.matches(taints, label_reqs):
                    continue
                (add_h if resched else rm_h)[j, g, slot_of[j]] += 1

    tot_add_v = add_v.sum(axis=0)
    tot_add_h = add_h.sum(axis=0)

    # ---- batched state ---------------------------------------------------
    eavail_b = np.broadcast_to(
        np.asarray(base.eavail), (B,) + base.eavail.shape
    ).copy()
    if singleton:
        for k in range(B):
            eavail_b[k, slot_of[k], :] = -1  # only candidate k removed
        # kept candidates' reschedulable pods stay counted; only lane k's
        # own pods move and its non-reschedulable riders vanish
        v_cnt_b = (
            np.asarray(base.v_cnt)[None] + (tot_add_v[None] - add_v) - rm_v
        )
        h_cnt_b = (
            np.asarray(base.h_cnt)[None] + (tot_add_h[None] - add_h) - rm_h
        )
    else:
        # prefix k (0-based) removes candidates[:k+1]
        cum_add_v = np.cumsum(add_v, axis=0)
        cum_rm_v = np.cumsum(rm_v, axis=0)
        cum_add_h = np.cumsum(add_h, axis=0)
        cum_rm_h = np.cumsum(rm_h, axis=0)
        for k in range(B):
            for j in range(k + 1):
                eavail_b[k, slot_of[j], :] = -1  # removed: fits nothing
        v_cnt_b = (
            np.asarray(base.v_cnt)[None]
            + (tot_add_v[None] - cum_add_v)
            - cum_rm_v
        )
        h_cnt_b = (
            np.asarray(base.h_cnt)[None]
            + (tot_add_h[None] - cum_add_h)
            - cum_rm_h
        )

    # int64 guard before the int32 device cast: a per-prefix count total
    # that cannot ride the kernel's int32 topology state must fall back to
    # the sequential scans, never wrap silently
    peak = max(
        int(np.abs(v_cnt_b).max(initial=0)),
        int(np.abs(h_cnt_b).max(initial=0)),
    )
    if peak >= (1 << 31):
        raise SweepUnsupported("per-prefix topology counts exceed int32")
    v_cnt_b = v_cnt_b.astype(np.int32)
    h_cnt_b = h_cnt_b.astype(np.int32)

    xs = sched._pod_xs(problem, order)
    P_pad = int(xs.valid.shape[0])
    valid_b = np.zeros((B, P_pad), bool)
    pp = np.asarray([pod_prefix[i] for i in order])
    for k in range(B):
        if singleton:
            valid_b[k, : len(order)] = (pp == k) | (pp < 0)
        else:
            valid_b[k, : len(order)] = pp <= k

    st_axes = K.State(
        active=None, count=None, rank=None, tmpl=None,
        creq=type(base.creq)(*(None,) * len(base.creq)),
        crequests=None, alive=None, cmax_alloc=None, n_claims=None,
        ereq=type(base.ereq)(*(None,) * len(base.ereq)),
        eavail=0, trem=None, v_cnt=0, h_cnt=0, rescap=None, held=None,
        hp_used=None,
    )
    xs_axes = K.PodX(
        preq=type(xs.preq)(*(None,) * len(xs.preq)),
        prequests=None, typeok=None, tol_t=None, tol_e=None,
        topo_kind=None, topo_gid=None, topo_sel=None,
        sel_v=None, sel_h=None, inv_h=None, own_h=None, valid=0,
        rrow=None, ntiers=None, hp_own=None, hp_conf=None,
    )
    st_b = base._replace(
        eavail=jnp.asarray(eavail_b),
        v_cnt=jnp.asarray(v_cnt_b),
        h_cnt=jnp.asarray(h_cnt_b),
    )
    xs_b = xs._replace(valid=jnp.asarray(valid_b))

    relax = bool((problem.ntiers_r > 1).any())
    sweep = jax.jit(
        jax.vmap(
            functools.partial(K.solve_scan, relax=relax),
            in_axes=(None, st_axes, xs_axes),
        )
    )
    with tr.span("dispatch", path="sweep_vmap", lanes=B) as dsp:
        st_out, kinds, slots, over, odo_b = sweep(tb, st_b, xs_b)
        kinds, n_claims, over, odo_steps = jax.device_get(
            (kinds, st_out.n_claims, over, odo_b.steps)
        )
        steps = int(np.asarray(odo_steps).sum())
        dsp["kernel"] = {"steps": steps, "lanes": B}
        tracing.KERNEL_ITERATIONS.inc({"path": "sweep"}, by=steps)
    tr.count("dispatches")
    tr.count("kernel_iterations", by=steps)
    tracing.SOLVE_DISPATCHES.inc({"path": "sweep"})
    kinds = np.asarray(kinds)  # [B, P_pad]
    n_claims = np.asarray(n_claims)  # [B]
    over = np.asarray(over)

    feasible = []
    for k in range(B):
        lane_pods = ((pp == k) | (pp < 0)) if singleton else (pp <= k)
        ok = (
            not bool(over[k])
            and int(n_claims[k]) <= 1
            and not np.any(
                (kinds[k, : len(order)] == K.KIND_FAIL) & lane_pods
            )
        )
        feasible.append(ok)
    return feasible


def singleton_feasibility(
    kube, cluster, cloud_provider, candidates: list[Candidate], options=None
) -> list[bool]:
    """[len(candidates)] — can candidate k ALONE be removed with all its
    pods rescheduling onto the remaining cluster plus at most one new
    node? Every candidate is an independent device lane."""
    return prefix_feasibility(
        kube, cluster, cloud_provider, candidates, options, singleton=True
    )


def sweep_first_n(consolidation, candidates: list[Candidate]):
    """Drop-in for MultiNodeConsolidation's prefix search: one batched
    feasibility sweep, then the real compute_consolidation on the largest
    feasible prefix (prices/spot rules byte-identical to the sequential
    path). Returns a Command."""
    from karpenter_tpu.controllers.disruption.types import Command

    feasible = prefix_feasibility(
        consolidation.kube,
        consolidation.cluster,
        consolidation.cloud,
        candidates,
        consolidation.opts,
    )
    for k in range(len(candidates), 0, -1):
        if not feasible[k - 1]:
            continue
        cmd = consolidation.compute_consolidation(candidates[:k])
        if cmd.decision != "no-op":
            return cmd
    return Command(reason=consolidation.reason)


# ---------------------------------------------------------------------------
# benchmark harness (BASELINE.json config 4)


def bench_sweep(n_nodes: int = 2000, n_candidates: int = 100) -> dict:
    """2k under-utilized nodes; compare one batched prefix sweep against the
    reference-style sequential binary search (per-probe full simulation)."""
    from karpenter_tpu.controllers.disruption.consolidation import (
        MultiNodeConsolidation,
    )
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.testing import fixtures

    from karpenter_tpu.api.objects import Budget

    op = Operator(clock=FakeClock(), force_oracle=False)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.reset_rng(7)
    fixtures.make_underutilized_fleet(op, n_nodes, max_ticks=400)
    op.clock.advance(30.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()

    args = (op.kube, op.cluster, op.cloud, op.clock)
    mnc = MultiNodeConsolidation(*args, options=op.opts, force_oracle=True)
    candidates = mnc.candidates()[:n_candidates]

    # batched sweep: warm once (compile), then steady state
    t0 = time.monotonic()
    feasible = prefix_feasibility(op.kube, op.cluster, op.cloud, candidates, op.opts)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    feasible = prefix_feasibility(op.kube, op.cluster, op.cloud, candidates, op.opts)
    sweep_s = time.monotonic() - t0

    # sequential binary search (reference method shape), oracle probes
    t0 = time.monotonic()
    cmd_binary = mnc.first_n_binary(candidates)
    binary_s = time.monotonic() - t0

    # binary search with TPU-simulated probes: pow2-bucketed pod AND
    # existing-slot shapes mean the ~log2(N) probes share a couple of
    # compiled kernels; warm once, then steady state
    mnc_tpu = MultiNodeConsolidation(*args, options=op.opts, force_oracle=False)
    t0 = time.monotonic()
    mnc_tpu.first_n_binary(candidates)
    tpu_first_s = time.monotonic() - t0
    t0 = time.monotonic()
    cmd_tpu = mnc_tpu.first_n_binary(candidates)
    tpu_binary_s = time.monotonic() - t0

    largest = max((i + 1 for i, f in enumerate(feasible) if f), default=0)
    return {
        "nodes": n_nodes,
        "prefixes_evaluated": len(candidates),
        "sweep_seconds": round(sweep_s, 3),
        "sweep_compile_seconds": round(compile_s, 1),
        "binary_search_seconds": round(binary_s, 3),
        "tpu_binary_seconds": round(tpu_binary_s, 3),
        "tpu_binary_compile_seconds": round(max(0.0, tpu_first_s - tpu_binary_s), 1),
        "speedup": round(binary_s / sweep_s, 2) if sweep_s else None,
        "tpu_binary_speedup": round(binary_s / tpu_binary_s, 2)
        if tpu_binary_s
        else None,
        "largest_feasible_prefix": largest,
        "binary_prefix": len(cmd_binary.candidates),
        "tpu_binary_prefix": len(cmd_tpu.candidates),
        "agree": largest == len(cmd_binary.candidates)
        and len(cmd_tpu.candidates) == len(cmd_binary.candidates),
    }


def bench_single_sweep(n_nodes: int = 1000, n_candidates: int = 100) -> dict:
    """Single-node consolidation: batched singleton lanes vs the
    reference's sequential per-candidate walk
    (singlenodeconsolidation.go:56). The fleet is fully feasible, so the
    sequential walk's first simulation already returns a command — the
    honest comparison is the FEASIBILITY phase: one singleton sweep over
    all candidates vs one sequential simulation per candidate."""
    import time as _t

    from karpenter_tpu.api.objects import Budget
    from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling
    from karpenter_tpu.controllers.disruption.consolidation import (
        SingleNodeConsolidation,
    )
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.testing import fixtures

    op = Operator(clock=FakeClock(), force_oracle=False)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.reset_rng(7)
    fixtures.make_underutilized_fleet(op, n_nodes, max_ticks=400)
    op.clock.advance(30.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()

    args = (op.kube, op.cluster, op.cloud, op.clock)
    snc = SingleNodeConsolidation(*args, options=op.opts, force_oracle=True)
    candidates = snc.candidates()[:n_candidates]

    t0 = _t.monotonic()
    feas = singleton_feasibility(op.kube, op.cluster, op.cloud, candidates, op.opts)
    compile_s = _t.monotonic() - t0
    t0 = _t.monotonic()
    feas = singleton_feasibility(op.kube, op.cluster, op.cloud, candidates, op.opts)
    sweep_s = _t.monotonic() - t0

    t0 = _t.monotonic()
    seq = []
    for c in candidates:
        sim = simulate_scheduling(
            op.kube, op.cluster, op.cloud, [c], op.opts, force_oracle=True
        )
        seq.append(
            sim.all_pods_scheduled() and len(sim.non_empty_new_claims()) <= 1
        )
    seq_s = _t.monotonic() - t0

    return {
        "nodes": n_nodes,
        "candidates": len(candidates),
        "sweep_seconds": round(sweep_s, 3),
        "sweep_compile_seconds": round(max(0.0, compile_s - sweep_s), 1),
        "sequential_seconds": round(seq_s, 3),
        "speedup": round(seq_s / sweep_s, 2) if sweep_s else None,
        "agree": feas == seq,
        "feasible_count": sum(feas),
    }
