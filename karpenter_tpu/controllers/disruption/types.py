"""Disruption candidates and commands.

Reference /root/reference/pkg/controllers/disruption/types.go:73-216.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    COND_EMPTY,
    NodePool,
    Pod,
)
from karpenter_tpu.controllers.state import StateNode
from karpenter_tpu.solver.nodes import SchedulingNodeClaim

# disruption reasons (reference apis/v1 DisruptionReason)
REASON_UNDERUTILIZED = "underutilized"
REASON_EMPTY = "empty"
REASON_DRIFTED = "drifted"


@dataclass
class Candidate:
    """types.go:73 Candidate: a disruptable node plus everything the
    decision needs."""

    state_node: StateNode
    node_pool: NodePool
    instance_type_name: str
    capacity_type: str
    zone: str
    price: float  # current offering price (MAX if unknown)
    reschedulable_pods: list[Pod] = field(default_factory=list)
    disruption_cost: float = 0.0

    @property
    def name(self) -> str:
        return self.state_node.name

    @property
    def nodepool_name(self) -> str:
        return self.node_pool.name

    def claim_name(self) -> Optional[str]:
        claim = self.state_node.node_claim
        return claim.name if claim is not None else None

    def is_empty(self) -> bool:
        return not self.reschedulable_pods

    def owned_by_static_nodepool(self) -> bool:
        """types.go:83: static pools scale via their replica controllers;
        only StaticDrift may disrupt them."""
        return self.node_pool.replicas is not None

    def condition(self, cond: str) -> bool:
        claim = self.state_node.node_claim
        return claim is not None and claim.status.conditions.get(cond) == "True"

    def consolidatable(self) -> bool:
        return self.condition(COND_CONSOLIDATABLE)

    def drifted(self) -> bool:
        return self.condition(COND_DRIFTED)

    def empty_condition(self) -> bool:
        return self.condition(COND_EMPTY)


DECISION_DELETE = "delete"
DECISION_REPLACE = "replace"
DECISION_NOOP = "no-op"


@dataclass
class Command:
    """types.go:150 Command: what to do with a candidate set."""

    reason: str
    candidates: list[Candidate] = field(default_factory=list)
    replacements: list[SchedulingNodeClaim] = field(default_factory=list)
    # node-count reservations held against a static pool's `nodes` limit
    # (statenodepool.go ReserveNodeCount); released on launch — or by the
    # controller if the command is discarded or fails validation
    reserved_pool: Optional[str] = None
    reserved_count: int = 0

    @property
    def decision(self) -> str:
        if not self.candidates:
            return DECISION_NOOP
        return DECISION_REPLACE if self.replacements else DECISION_DELETE

    def __repr__(self) -> str:
        return (
            f"Command({self.decision}, reason={self.reason}, "
            f"candidates={[c.name for c in self.candidates]}, "
            f"replacements={len(self.replacements)})"
        )


def command_savings(cmd: Command) -> float:
    """$/hour saved by executing the command: the removed candidates'
    current offering prices minus (for replace) the cheapest launch price
    the replacement could resolve to. consolidation.go:199 filterByPrice
    bounds every replacement option strictly below the current total, so
    this is positive for every non-noop command — the removal-set
    search's ranking objective (setsweep.py), where the prefix search's
    objective was simply the prefix length.

    A candidate with an unknown price carries MAX_FLOAT
    (helpers.py _candidate_price); such a command's savings are
    unknowable, not infinite, so it ranks at 0.0 rather than poisoning
    the search with inf/NaN arithmetic."""
    import math

    from karpenter_tpu.cloudprovider.types import MAX_FLOAT

    if not cmd.candidates:
        return 0.0
    if any(c.price >= MAX_FLOAT for c in cmd.candidates):
        return 0.0
    saved = sum(c.price for c in cmd.candidates)
    for claim in cmd.replacements:
        prices = [
            it.offerings.available().cheapest_launch_price(claim.requirements)
            for it in claim.instance_type_options
        ]
        prices = [p for p in prices if p < MAX_FLOAT]
        saved -= min(prices) if prices else MAX_FLOAT
    return saved if math.isfinite(saved) else 0.0


POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def eviction_cost(pod: Pod) -> float:
    """utils/disruption/disruption.go:49 EvictionCost, exactly: base 1.0 +
    deletion-cost annotation / 2^27 + priority / 2^25, clamped to
    [-10, 10]. A malformed annotation is ignored (the reference logs and
    continues)."""
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / (2.0 ** 27)
        except ValueError:
            pass
    cost += float(pod.priority) / (2.0 ** 25)
    return max(-10.0, min(10.0, cost))


def lifetime_remaining(clock, claim) -> float:
    """utils/disruption/disruption.go:37 LifetimeRemaining: fraction of
    expireAfter left, in [0, 1]; 1.0 when expiry is disabled — nodes near
    expiry are cheaper to disrupt."""
    if claim is None or claim.expire_after_seconds is None:
        return 1.0
    total = float(claim.expire_after_seconds)
    if total <= 0:
        return 1.0
    age = clock.now() - claim.metadata.creation_timestamp
    return max(0.0, min(1.0, (total - age) / total))


def disruption_cost(pods: list[Pod], clock=None, claim=None) -> float:
    """ReschedulingCost x LifetimeRemaining (disruption.go:72 +
    types.go:132): the candidate-ordering key."""
    cost = sum(eviction_cost(p) for p in pods)
    if clock is not None:
        cost *= lifetime_remaining(clock, claim)
    return cost
