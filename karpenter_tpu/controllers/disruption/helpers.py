"""Disruption helpers: SimulateScheduling (THE consolidation primitive),
candidate discovery, and disruption budgets.

Reference /root/reference/pkg/controllers/disruption/helpers.go:
- SimulateScheduling :52-143
- GetCandidates :174, candidate filters in types.go:73-134
- BuildDisruptionBudgetMapping :231-279
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.cloudprovider.types import MAX_FLOAT
from karpenter_tpu.controllers.disruption.types import Candidate, disruption_cost
from karpenter_tpu.controllers.state import Cluster, cluster_source, is_reschedulable
from karpenter_tpu.options import Options
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver import HybridScheduler, Results, SchedulerOptions, Topology
from karpenter_tpu.utils.pdb import PDBLimits


@dataclass
class SimResults:
    """helpers.go:34 scheduling results wrapper."""

    results: Results
    pods: list[Pod]
    used_tpu: bool = False  # which solver produced the simulation

    def all_pods_scheduled(self) -> bool:
        return not self.results.pod_errors and not self.results.timed_out

    def non_empty_new_claims(self):
        return [c for c in self.results.new_node_claims if c.pods]


def simulate_scheduling(
    kube,
    cluster: Cluster,
    cloud_provider,
    candidates: list[Candidate],
    options: Optional[Options] = None,
    force_oracle: bool = False,
) -> SimResults:
    """helpers.go:52 SimulateScheduling: solve the cluster as if the
    candidates were gone — their reschedulable pods plus all pending pods
    against every *other* node."""
    opts = options or Options()
    candidate_names = {c.name for c in candidates}

    # deleting nodes' pods + candidates' pods + pending pods (helpers.go:84)
    pods: list[Pod] = []
    seen: set[str] = set()

    def add(ps):
        for p in ps:
            if p.uid not in seen:
                seen.add(p.uid)
                pods.append(p.deep_copy())

    for c in candidates:
        add(c.reschedulable_pods)
    for sn in cluster.state_nodes():
        if sn.name in candidate_names:
            continue
        if sn.marked_for_deletion or sn.deleting():
            add(p for p in cluster.pods_on(sn.name) if is_reschedulable(p))
    add(kube.pending_pods())

    node_pools = [np for np in kube.list("NodePool") if np.replicas is None]
    its_by_pool = {np.name: cloud_provider.get_instance_types(np) for np in node_pools}
    daemonset_pods = [ds.pod_template for ds in kube.list("DaemonSet")]

    views = [
        v
        for v in cluster.schedulable_node_views()
        if v.name not in candidate_names
    ]
    # pods on removed nodes aren't "scheduled" in the sim
    topology = Topology(
        node_pools,
        its_by_pool,
        pods,
        cluster=cluster_source(kube, cluster, frozenset(candidate_names)),
        state_node_views=views,
    )
    scheduler = HybridScheduler(
        node_pools,
        its_by_pool,
        topology,
        views,
        daemonset_pods,
        SchedulerOptions(
            timeout_seconds=opts.solve_timeout_seconds,
            tpu_min_pods=opts.tpu_min_pods,
        ),
        force_oracle=force_oracle,
    )
    results = scheduler.solve(pods)
    return SimResults(
        results=results, pods=pods, used_tpu=bool(scheduler.used_tpu)
    )


# ---------------------------------------------------------------------------
# candidates


def _build_candidate(
    sn, nodepools, cloud_provider, pdb_limits: PDBLimits, now: float
) -> Optional[Candidate]:
    """types.go:73 NewCandidate filters + statenode.go:202
    ValidateNodeDisruptable."""
    if not sn.owned() or sn.node is None or sn.node_claim is None:
        return None
    if not sn.registered() or not sn.initialized():
        return None
    if sn.marked_for_deletion or sn.deleting():
        return None
    if sn.nominated(now):
        return None
    labels = sn.labels()
    np_name = labels.get(well_known.NODEPOOL_LABEL_KEY)
    node_pool = nodepools.get(np_name)
    if node_pool is None:
        return None
    # do-not-disrupt on the node (statenode.go:234); pod-level checks happen
    # in build_candidates where the pod list is resolved
    if sn.node.metadata.annotations.get(well_known.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
        return None
    return Candidate(
        state_node=sn,
        node_pool=node_pool,
        instance_type_name=labels.get(well_known.INSTANCE_TYPE_LABEL_KEY, ""),
        capacity_type=labels.get(well_known.CAPACITY_TYPE_LABEL_KEY, ""),
        zone=labels.get(well_known.TOPOLOGY_ZONE_LABEL_KEY, ""),
        price=MAX_FLOAT,
        reschedulable_pods=[],
    )


def build_candidates(
    kube,
    cluster: Cluster,
    cloud_provider,
    clock,
    should_disrupt: Callable[[Candidate], bool],
    disruption_class: str = "graceful",
) -> list[Candidate]:
    """GetCandidates with pods/prices resolved (the working entry point).

    disruption_class (types.go:47-48 + types.go:118): GRACEFUL methods
    (consolidation, emptiness) always respect blocking PDBs and the
    do-not-disrupt annotation; EVENTUAL methods (drift, static drift)
    on a claim with a TerminationGracePeriod may disrupt anyway — the TGP
    bounds how long those pods can hold the node."""
    nodepools = {np.name: np for np in kube.list("NodePool")}
    pdb_limits = PDBLimits.from_kube(kube)
    its_cache: dict[str, dict[str, object]] = {}
    now = clock.now()
    out: list[Candidate] = []
    for sn in cluster.state_nodes():
        c = _build_candidate(sn, nodepools, cloud_provider, pdb_limits, now)
        if c is None:
            continue
        pods = cluster.pods_on(sn.name)
        tgp_eventual = (
            disruption_class == "eventual"
            and sn.node_claim is not None
            and sn.node_claim.termination_grace_period_seconds is not None
        )
        # pods blocking disruption entirely (statenode.go:234): do-not-disrupt
        if not tgp_eventual and any(
            p.metadata.annotations.get(well_known.DO_NOT_DISRUPT_ANNOTATION_KEY)
            == "true"
            for p in pods
        ):
            continue
        # PDB check: every evictable pod must be currently evictable
        blocked = False
        if not tgp_eventual:
            for p in pods:
                ok, _ = pdb_limits.can_evict(p)
                if not ok or pdb_limits.is_fully_blocked(p) is not None:
                    blocked = True
                    break
        if blocked:
            continue
        c.reschedulable_pods = [p for p in pods if is_reschedulable(p)]
        # cost over ALL pods on the candidate, not just reschedulable ones
        # (types.go:131-132 — "we get the disruption cost from all pods")
        c.disruption_cost = disruption_cost(
            pods, clock, c.state_node.node_claim
        )
        c.price = _candidate_price(c, cloud_provider, its_cache)
        if should_disrupt(c):
            out.append(c)
    return out


def _candidate_price(c: Candidate, cloud_provider, its_cache) -> float:
    """consolidation.go:314 getCandidatePrices: the price of the candidate's
    current offering."""
    pool_types = its_cache.get(c.nodepool_name)
    if pool_types is None:
        pool_types = {
            it.name: it for it in cloud_provider.get_instance_types(c.node_pool)
        }
        its_cache[c.nodepool_name] = pool_types
    it = pool_types.get(c.instance_type_name)
    if it is None:
        return MAX_FLOAT
    reqs = Requirements.from_labels(
        {
            well_known.CAPACITY_TYPE_LABEL_KEY: c.capacity_type,
            well_known.TOPOLOGY_ZONE_LABEL_KEY: c.zone,
        }
    )
    for o in it.offerings:
        if o.available and o.requirements.is_compatible(reqs):
            return o.price
    return MAX_FLOAT


# ---------------------------------------------------------------------------
# budgets


@dataclass
class BudgetMapping:
    """helpers.go:231 BuildDisruptionBudgetMapping: per nodepool, how many
    more nodes may be disrupted right now for a given reason."""

    allowed: dict[str, int] = field(default_factory=dict)

    def can_disrupt(self, nodepool: str, n: int = 1) -> bool:
        return self.allowed.get(nodepool, 0) >= n

    def consume(self, nodepool: str, n: int = 1) -> None:
        self.allowed[nodepool] = max(0, self.allowed.get(nodepool, 0) - n)


def build_budget_mapping(kube, cluster: Cluster, reason: str) -> BudgetMapping:
    mapping = BudgetMapping()
    # count nodes per nodepool and nodes already being disrupted
    totals: dict[str, int] = {}
    disrupting: dict[str, int] = {}
    for sn in cluster.state_nodes():
        np_name = sn.nodepool_name
        if np_name is None:
            continue
        totals[np_name] = totals.get(np_name, 0) + 1
        if sn.marked_for_deletion or sn.deleting():
            disrupting[np_name] = disrupting.get(np_name, 0) + 1
    for np in kube.list("NodePool"):
        total = totals.get(np.name, 0)
        allowed = total  # no budgets = unlimited up to pool size
        for budget in np.disruption.budgets:
            if budget.reasons and reason not in budget.reasons:
                continue
            raw = budget.nodes.strip()
            if raw.endswith("%"):
                # nodepool.go:359 GetScaledValueFromIntOrPercent(roundUp=true):
                # a 10% budget on a 5-node pool still allows 1 disruption
                limit = math.ceil(total * float(raw[:-1]) / 100.0)
            else:
                limit = int(raw)
            allowed = min(allowed, limit)
        mapping.allowed[np.name] = max(0, allowed - disrupting.get(np.name, 0))
    return mapping
