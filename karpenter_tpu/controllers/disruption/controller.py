"""The disruption controller: the 10s singleton loop trying methods in
order — Emptiness, Drift, MultiNodeConsolidation, SingleNodeConsolidation —
first success wins.

Reference /root/reference/pkg/controllers/disruption/controller.go:69-227.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.controllers.disruption.consolidation import (
    DriftConsolidation,
    EmptinessConsolidation,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.queue import (
    VALIDATION_TTL_SECONDS,
    OrchestrationQueue,
    Validator,
)
from karpenter_tpu.controllers.disruption.staticdrift import StaticDrift
from karpenter_tpu.controllers.disruption.types import Command
from karpenter_tpu.controllers.state import DISRUPTED_TAINT
from karpenter_tpu.events import Recorder
from karpenter_tpu.options import Options
from karpenter_tpu import logging, metrics

EVAL_DURATION = metrics.REGISTRY.histogram(
    "karpenter_disruption_evaluation_duration_seconds",
    "Duration of disruption evaluation loops.",
    ("method",),
)


class DisruptionController:
    def __init__(
        self,
        kube,
        cluster,
        cloud_provider,
        provisioner,
        clock,
        options: Optional[Options] = None,
        recorder: Optional[Recorder] = None,
        force_oracle: bool = False,
        validation_ttl_seconds: float = VALIDATION_TTL_SECONDS,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.opts = options or Options()
        self.recorder = recorder or Recorder(clock)
        self.queue = OrchestrationQueue(
            kube, cluster, provisioner, clock, self.recorder
        )
        args = (kube, cluster, cloud_provider, clock)
        kwargs = dict(
            options=self.opts, recorder=self.recorder, force_oracle=force_oracle
        )
        # NewMethods order (controller.go:98); the multi-node search
        # enters the strategy ladder at the configured rung (sets ->
        # batched prefixes -> binary, docs/consolidation.md) and falls
        # down it automatically on SweepUnsupported
        self.methods = [
            EmptinessConsolidation(*args, **kwargs),
            StaticDrift(*args, **kwargs),
            DriftConsolidation(*args, **kwargs),
            MultiNodeConsolidation(
                *args, sweep=self.opts.multinode_sweep_strategy, **kwargs
            ),
            SingleNodeConsolidation(*args, **kwargs),
        ]
        self.validator = Validator(
            kube, cluster, cloud_provider, clock, self.opts, force_oracle
        )
        self.validation_ttl = validation_ttl_seconds
        self._pending_validation: Optional[tuple[float, Command]] = None
        self._last_run = -1e18
        self.log = logging.root.named("disruption")

    def reconcile(self) -> Optional[Command]:
        """One loop iteration (controller.go:121). Returns the command that
        started executing, if any."""
        now = self.clock.now()
        self.queue.reconcile()
        # a command awaiting its validation TTL?
        if self._pending_validation is not None:
            decided_at, cmd = self._pending_validation
            if now - decided_at < self.validation_ttl:
                return None
            self._pending_validation = None
            if self.validator.validate(cmd):
                self.log.info(
                    "executing disruption command",
                    reason=cmd.reason,
                    decision=cmd.decision,
                    candidates=len(cmd.candidates),
                    replacements=len(cmd.replacements),
                )
                self.queue.start_command(cmd)
                return cmd
            self.log.info(
                "disruption command failed validation",
                reason=cmd.reason,
                candidates=len(cmd.candidates),
            )
            self._release_reservation(cmd)
            return None
        if now - self._last_run < self.opts.disruption_poll_seconds:
            return None
        self._last_run = now
        if not self.cluster.synced(self.kube):
            return None
        if self.queue.busy:
            return None  # one command at a time (the reference serializes
            # via candidate taints; a single queue keeps it simple)
        self._clean_stale_taints()
        for method in self.methods:
            label = type(method).__name__
            with EVAL_DURATION.measure({"method": label}):
                commands = method.compute_commands()
            if not commands:
                continue
            cmd = commands[0]
            # this controller serializes one command at a time; any node-
            # count reservations held by the commands it won't execute must
            # be handed back (the next reconcile re-reserves)
            for other in commands[1:]:
                self._release_reservation(other)
            self.log.debug(
                "disruption command proposed",
                method=label,
                reason=cmd.reason,
                decision=cmd.decision,
                candidates=len(cmd.candidates),
            )
            self._pending_validation = (now, cmd)
            return None
        # nothing to do: the cluster is consolidated (cluster.go:550)
        self.cluster.mark_consolidated()
        return None

    def _release_reservation(self, cmd: Command) -> None:
        if cmd.reserved_pool and cmd.reserved_count > 0:
            self.cluster.nodepool_state.release_node_count(
                cmd.reserved_pool, cmd.reserved_count
            )
            cmd.reserved_count = 0

    def _clean_stale_taints(self) -> None:
        """controller.go:143: nodes tainted for disruption but no longer
        part of any in-flight command get un-tainted."""
        in_flight_names = {
            c.name
            for item in self.queue.in_flight
            for c in item.command.candidates
        }
        pending = (
            {c.name for c in self._pending_validation[1].candidates}
            if self._pending_validation is not None
            else set()
        )
        keep = in_flight_names | pending
        for node in self.kube.list("Node"):
            if node.name in keep or DISRUPTED_TAINT not in node.taints:
                continue
            sn = self.cluster.node_by_name(node.name)
            if sn is not None and (sn.deleting() or sn.marked_for_deletion):
                continue
            node.taints = [t for t in node.taints if t != DISRUPTED_TAINT]
            try:
                self.kube.update("Node", node)
            except Exception:
                pass
