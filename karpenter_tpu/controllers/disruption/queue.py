"""Disruption validation and orchestration.

Reference /root/reference/pkg/controllers/disruption/:
- validation.go:52-316 (Validator: re-check a command after a TTL so pod
  churn between decision and execution can veto it)
- queue.go:94-412 (orchestration: taint -> launch replacements -> wait for
  readiness -> delete originals; rollback on unrecoverable errors)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.api.objects import COND_INITIALIZED
from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling
from karpenter_tpu.controllers.disruption.types import (
    DECISION_DELETE,
    DECISION_REPLACE,
    Command,
)
from karpenter_tpu.controllers.kube import NotFound
from karpenter_tpu.controllers.state import DISRUPTED_TAINT, is_reschedulable
from karpenter_tpu.events import Event
from karpenter_tpu import metrics

# validation.go:46 consolidation TTL
VALIDATION_TTL_SECONDS = 15.0

COMMANDS_EXECUTED = metrics.REGISTRY.counter(
    "karpenter_disruption_commands_total",
    "Disruption commands by decision and reason.",
    ("decision", "reason"),
)
NODES_DISRUPTED = metrics.REGISTRY.counter(
    "karpenter_nodes_disrupted_total",
    "Nodes disrupted, by reason.",
    ("nodepool", "reason"),
)


class Validator:
    """validation.go:52: after the TTL, the candidates must still be
    disruptable and the consolidation decision must still hold."""

    def __init__(self, kube, cluster, cloud, clock, options, force_oracle=False):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock
        self.opts = options
        self.force_oracle = force_oracle

    def validate(self, cmd: Command) -> bool:
        for c in cmd.candidates:
            sn = self.cluster.node_by_name(c.name)
            if sn is None or sn.deleting() or sn.marked_for_deletion:
                return False
            if sn.nominated(self.clock.now()):
                return False  # the provisioner wants this node
        if all(c.owned_by_static_nodepool() for c in cmd.candidates):
            # StaticDrift is an eventual-class method: its replacement is a
            # workload-independent template launch, so the consolidation
            # re-simulation (which excludes static pools, helpers.py:75)
            # must not veto it — the reference never validates it
            # (controller.go dispatches validation per method class)
            return True
        if cmd.decision == DECISION_DELETE and all(
            c.is_empty() for c in cmd.candidates
        ):
            # emptiness validation: still empty of *reschedulable* pods
            # (emptiness.go:67 — daemonsets/terminal pods don't count)
            for c in cmd.candidates:
                if any(
                    is_reschedulable(p) for p in self.cluster.pods_on(c.name)
                ):
                    return False
            return True
        # consolidation validation: re-simulate (validation.go:152)
        sim = simulate_scheduling(
            self.kube,
            self.cluster,
            self.cloud,
            cmd.candidates,
            self.opts,
            force_oracle=self.force_oracle,
        )
        if not sim.all_pods_scheduled():
            return False
        new_claims = sim.non_empty_new_claims()
        if cmd.decision == DECISION_DELETE:
            return not new_claims
        return len(new_claims) <= len(cmd.replacements)


@dataclass
class _InFlight:
    command: Command
    replacement_names: list[str] = field(default_factory=list)
    launched: bool = False


class OrchestrationQueue:
    """queue.go:94: executes validated commands. Because SimKube is
    synchronous, the retry machinery reduces to: taint+mark, create
    replacement claims, then on every reconcile check replacement readiness
    and finally delete the originals (rollback if a replacement failed)."""

    def __init__(self, kube, cluster, provisioner, clock, recorder):
        self.kube = kube
        self.cluster = cluster
        self.provisioner = provisioner
        self.clock = clock
        self.recorder = recorder
        self.in_flight: list[_InFlight] = []

    def start_command(self, cmd: Command) -> None:
        """queue.go:306 StartCommand: taint + MarkForDeletion + launch
        replacements."""
        names = [c.name for c in cmd.candidates]
        self.cluster.mark_for_deletion(*names)
        # queue.go:279: static candidates become pending-disruption (their
        # replacement is being created; StaticProvisioning must not race)
        for c in cmd.candidates:
            claim_name = c.claim_name()
            if c.owned_by_static_nodepool() and claim_name is not None:
                self.cluster.nodepool_state.mark_pending_disruption(
                    c.nodepool_name, claim_name
                )
        for c in cmd.candidates:
            node = self.kube.try_get("Node", c.name)
            if node is not None and DISRUPTED_TAINT not in node.taints:
                node.taints = list(node.taints) + [DISRUPTED_TAINT]
                try:
                    self.kube.update("Node", node)
                except Exception:
                    pass
        item = _InFlight(command=cmd)
        if cmd.replacements:
            from karpenter_tpu.api.objects import NodeClaim as ApiNodeClaim
            from karpenter_tpu.solver.oracle import Results

            bare = [r for r in cmd.replacements if isinstance(r, ApiNodeClaim)]
            solved = [r for r in cmd.replacements if not isinstance(r, ApiNodeClaim)]
            # StaticDrift replacements are bare template launches with no
            # pods (staticdrift.go:95) — create them directly and convert
            # their node-count reservation (provisioner.go:166)
            for nc in bare:
                stored = self.kube.create("NodeClaim", nc)
                item.replacement_names.append(stored.name)
                pool = stored.nodepool_name
                if pool:
                    # launch converts the reservation to an active claim
                    self.cluster.nodepool_state.release_node_count(pool, 1)
                    cmd.reserved_count = max(0, cmd.reserved_count - 1)
            if solved:
                fake_results = Results(
                    new_node_claims=solved,
                    existing_nodes=[],
                    pod_errors={},
                )
                created = self.provisioner.create_node_claims(fake_results)
                item.replacement_names += [c.name for c in created]
        item.launched = True
        self.in_flight.append(item)
        COMMANDS_EXECUTED.inc(
            {"decision": cmd.decision, "reason": cmd.reason}
        )
        for c in cmd.candidates:
            NODES_DISRUPTED.inc(
                {"nodepool": c.nodepool_name, "reason": cmd.reason}
            )
            self.recorder.publish(
                Event(
                    "Node", c.name, "Normal", "DisruptionTerminating",
                    f"disrupting via {cmd.reason} ({cmd.decision})",
                )
            )

    def reconcile(self) -> None:
        """queue.go:137: for each in-flight command, wait for replacements
        to initialize, then delete the originals."""
        remaining: list[_InFlight] = []
        for item in self.in_flight:
            done, failed = self._replacements_state(item)
            if failed:
                # rollback (queue.go:181 waitOrTerminate unrecoverable)
                self.cluster.unmark_for_deletion(
                    *[c.name for c in item.command.candidates]
                )
                for c in item.command.candidates:
                    node = self.kube.try_get("Node", c.name)
                    if node is not None and DISRUPTED_TAINT in node.taints:
                        node.taints = [
                            t for t in node.taints if t != DISRUPTED_TAINT
                        ]
                        try:
                            self.kube.update("Node", node)
                        except Exception:
                            pass
                continue
            if not done:
                remaining.append(item)
                continue
            for c in item.command.candidates:
                claim_name = c.claim_name()
                try:
                    if claim_name is not None:
                        self.kube.delete("NodeClaim", claim_name)
                    else:
                        self.kube.delete("Node", c.name)
                except NotFound:
                    pass
        self.in_flight = remaining

    def _replacements_state(self, item: _InFlight) -> tuple[bool, bool]:
        """(all ready, any failed)"""
        if not item.replacement_names:
            return True, False
        ready = 0
        for name in item.replacement_names:
            claim = self.kube.try_get("NodeClaim", name)
            if claim is None:
                return False, True  # liveness deleted it -> roll back
            if claim.status.conditions.get(COND_INITIALIZED) == "True":
                ready += 1
        return ready == len(item.replacement_names), False

    @property
    def busy(self) -> bool:
        return bool(self.in_flight)
