"""Consolidation methods: Emptiness, Drift, MultiNode, SingleNode.

Reference /root/reference/pkg/controllers/disruption/:
- consolidation.go:53-332 (base: ShouldDisrupt gates, computeConsolidation
  delete-vs-replace decision, spot-to-spot rules, price lookup)
- multinodeconsolidation.go:51-236 (first-N batch search)
- singlenodeconsolidation.go:56-175
- emptiness.go:31-115, drift.go:38-116

TPU twist (SURVEY.md §7 M7): where the reference binary-searches the
candidate prefix with ~log2(N) sequential re-simulations, the multi-node
method here can evaluate every prefix in one *batched sweep* — each prefix's
reschedule simulation runs through the same HybridScheduler, so supported
problems ride the TPU path; the sweep strategy (all prefixes vs binary
search) is selectable and produces identical commands (the largest feasible
prefix), enforced by tests.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.cloudprovider.types import MAX_FLOAT
from karpenter_tpu.controllers.disruption.helpers import (
    BudgetMapping,
    SimResults,
    build_budget_mapping,
    build_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.disruption.types import (
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
    Candidate,
    Command,
)
from karpenter_tpu.options import Options

# consolidation.go:49 MinInstanceTypesForSpotToSpotConsolidation
MIN_TYPES_FOR_SPOT_TO_SPOT = 15
# multinodeconsolidation.go:86 max candidates considered per pass
MAX_MULTI_NODE_CANDIDATES = 100


class ConsolidationBase:
    """consolidation.go:53 consolidation: shared gates + decision logic."""

    reason = REASON_UNDERUTILIZED

    def __init__(
        self,
        kube,
        cluster,
        cloud_provider,
        clock,
        options: Optional[Options] = None,
        recorder=None,
        force_oracle: bool = False,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.opts = options or Options()
        self.recorder = recorder
        self.force_oracle = force_oracle

    # -- gates ------------------------------------------------------------

    def should_disrupt(self, c: Candidate) -> bool:
        """consolidation.go:89 ShouldDisrupt: nodepool allows consolidation
        and the claim's Consolidatable condition is True."""
        if c.owned_by_static_nodepool():  # consolidation.go:91
            return False
        policy = c.node_pool.disruption.consolidation_policy
        if policy == "WhenEmpty" and not c.is_empty():
            return False
        return c.consolidatable()

    # graceful methods always respect blocking PDBs / do-not-disrupt;
    # eventual methods override (types.go:47-48)
    disruption_class = "graceful"

    def candidates(self) -> list[Candidate]:
        out = build_candidates(
            self.kube, self.cluster, self.cloud, self.clock,
            self.should_disrupt, disruption_class=self.disruption_class,
        )
        # consolidation.go:127 sortCandidates: cheapest disruption first
        out.sort(key=lambda c: (c.disruption_cost, c.name))
        return out

    # -- the decision ------------------------------------------------------

    def compute_consolidation(self, candidates: list[Candidate]) -> Command:
        """consolidation.go:137 computeConsolidation: simulate removal; all
        pods must land; delete if no new node needed, else replace with at
        most one strictly-cheaper node."""
        if not candidates:
            return Command(reason=self.reason)
        sim = simulate_scheduling(
            self.kube,
            self.cluster,
            self.cloud,
            candidates,
            self.opts,
            force_oracle=self.force_oracle,
        )
        if not sim.all_pods_scheduled():
            return Command(reason=self.reason)
        new_claims = sim.non_empty_new_claims()
        if not new_claims:
            return Command(reason=self.reason, candidates=list(candidates))
        if len(new_claims) > 1:
            # multi-node replacement is never a win (consolidation.go:184)
            return Command(reason=self.reason)

        claim = new_claims[0]
        current_price = sum(c.price for c in candidates)
        if current_price >= MAX_FLOAT:
            return Command(reason=self.reason)

        # the replacement must be strictly cheaper: filter its instance
        # types to those under the current total price
        # (consolidation.go:199 filterByPrice)
        cheaper = type(claim.instance_type_options)(
            it
            for it in claim.instance_type_options
            if it.offerings.available().cheapest_launch_price(claim.requirements)
            < current_price
        )
        if not cheaper:
            return Command(reason=self.reason)

        # spot-to-spot (consolidation.go:237): all-spot candidates replaced
        # by spot require >= 15 cheaper types (flexibility floor) unless the
        # feature gate is off, in which case skip entirely
        all_spot = all(
            c.capacity_type == well_known.CAPACITY_TYPE_SPOT for c in candidates
        )
        replacement_allows_spot = any(
            o.capacity_type() == well_known.CAPACITY_TYPE_SPOT
            for it in cheaper
            for o in it.offerings.available()
        )
        if all_spot and replacement_allows_spot:
            if not self.opts.feature_gates.spot_to_spot_consolidation:
                return Command(reason=self.reason)
            if len(candidates) == 1 and len(cheaper) < MIN_TYPES_FOR_SPOT_TO_SPOT:
                return Command(reason=self.reason)
            if len(candidates) == 1:
                # single spot->spot: restrict to the 15 cheapest types
                # (multinodeconsolidation.go:187 filterOutSameInstanceType
                # analog, consolidation.go:291)
                ordered = cheaper.order_by_price(claim.requirements)
                cheaper = type(cheaper)(ordered[:MIN_TYPES_FOR_SPOT_TO_SPOT])

        claim.instance_type_options = cheaper
        return Command(
            reason=self.reason, candidates=list(candidates), replacements=[claim]
        )


class EmptinessConsolidation(ConsolidationBase):
    """emptiness.go:31 Emptiness: delete empty consolidatable nodes —
    no simulation needed."""

    reason = REASON_EMPTY

    def should_disrupt(self, c: Candidate) -> bool:
        if c.owned_by_static_nodepool():  # emptiness.go:43
            return False
        return c.is_empty() and c.consolidatable()

    def compute_commands(self) -> list[Command]:
        candidates = self.candidates()
        if not candidates:
            return []
        budgets = build_budget_mapping(self.kube, self.cluster, self.reason)
        allowed = []
        for c in candidates:
            if budgets.can_disrupt(c.nodepool_name):
                budgets.consume(c.nodepool_name)
                allowed.append(c)
        if not allowed:
            return []
        return [Command(reason=self.reason, candidates=allowed)]


class DriftConsolidation(ConsolidationBase):
    """drift.go:38 Drift: replace drifted nodes, budget-gated, one at a
    time in drift-condition order. Drift is an EVENTUAL disruption method
    (drift.go:111): a TerminationGracePeriod on the claim lets it proceed
    past do-not-disrupt pods and blocking PDBs."""

    reason = REASON_DRIFTED
    disruption_class = "eventual"

    def should_disrupt(self, c: Candidate) -> bool:
        return not c.owned_by_static_nodepool() and c.drifted()  # drift.go:56

    def compute_commands(self) -> list[Command]:
        candidates = self.candidates()
        budgets = build_budget_mapping(self.kube, self.cluster, self.reason)
        for c in candidates:
            if not budgets.can_disrupt(c.nodepool_name):
                continue
            if c.is_empty():
                return [Command(reason=self.reason, candidates=[c])]
            sim = simulate_scheduling(
                self.kube, self.cluster, self.cloud, [c], self.opts,
                force_oracle=self.force_oracle,
            )
            if not sim.all_pods_scheduled():
                continue
            return [
                Command(
                    reason=self.reason,
                    candidates=[c],
                    replacements=sim.non_empty_new_claims(),
                )
            ]
        return []


class MultiNodeConsolidation(ConsolidationBase):
    """multinodeconsolidation.go:51: find the best removal set among the
    disruption-cost-sorted candidates replaceable by <= 1 new node.

    The reference only ever searches PREFIXES of the cost order
    (firstNConsolidationOption's binary search); the four-rung strategy
    ladder here (docs/consolidation.md) widens that to arbitrary removal
    sets when the tensor encoding supports it, falling back rung by rung
    on SweepUnsupported:

      sets    — bounded exhaustive search over arbitrary removal sets,
                one batched device dispatch per proposal round
                (disruption/setsweep.py, round 6; strictly subsumes the
                prefix sweep and always materializes the largest
                feasible prefix as a backstop)
      batched — every prefix in ONE device invocation via the
                delta-state kernel (disruption/sweep.py, round 4;
                measured 1.35x the sequential bisection at 2k nodes x
                100 prefixes, BENCH_DETAIL c4)
      binary  — the reference's O(log N) bisection with full
                simulations per probe (multinodeconsolidation.go:116)
    Every rung materializes its result through the same
    compute_consolidation, so prices, spot rules, and replacements are
    byte-identical across rungs; the sequential simulator stays the
    bit-exact referee (tests/test_setsweep.py parity matrix)."""

    def __init__(self, *args, sweep: str = "sets", **kwargs):
        super().__init__(*args, **kwargs)
        # sweep is env-overridable (KARPENTER_MULTINODE_SWEEP_STRATEGY);
        # fail fast with the valid rungs, not an opaque assert (which
        # python -O would strip into a mid-reconcile KeyError)
        if sweep not in ("sets", "batched", "binary"):
            raise ValueError(
                f"unknown multi-node sweep strategy {sweep!r}; "
                "expected one of: sets, batched, binary"
            )
        self.sweep = sweep

    def compute_commands(self) -> list[Command]:
        candidates = self.candidates()
        if not candidates:
            return []
        budgets = build_budget_mapping(self.kube, self.cluster, self.reason)
        # budget-trim the prefix per nodepool (controller enforces globally;
        # trimming here keeps the search honest)
        trimmed: list[Candidate] = []
        counts: dict[str, int] = {}
        for c in candidates[:MAX_MULTI_NODE_CANDIDATES]:
            n = counts.get(c.nodepool_name, 0)
            if budgets.can_disrupt(c.nodepool_name, n + 1):
                counts[c.nodepool_name] = n + 1
                trimmed.append(c)
        if not trimmed:
            return []
        search = {
            "sets": self.first_n_sets,
            "batched": self.first_n_batched,
            "binary": self.first_n_binary,
        }[self.sweep]
        cmd = search(trimmed)
        return [cmd] if cmd.candidates else []

    # -- search strategies -------------------------------------------------

    def first_n_binary(self, candidates: list[Candidate]) -> Command:
        """multinodeconsolidation.go:116 firstNConsolidationOption: binary
        search over the prefix length (the reference's sequential method)."""
        lo, hi = 1, len(candidates)
        best = Command(reason=self.reason)
        deadline = (
            self.clock.now() + self.opts.multinode_consolidation_timeout_seconds
        )
        while lo <= hi:
            if self.clock.now() > deadline:
                break
            mid = (lo + hi) // 2
            cmd = self.compute_consolidation(candidates[:mid])
            if cmd.candidates:
                best = cmd
                lo = mid + 1
            else:
                hi = mid - 1
        return best

    def first_n_batched(self, candidates: list[Candidate]) -> Command:
        """Rung 2: ONE device invocation evaluates the feasibility of
        every candidate prefix simultaneously (disruption/sweep.py), then
        the real compute_consolidation materializes the command for the
        largest feasible prefix — prices, spot rules, and replacements
        byte-identical to the sequential method. Shapes the sweep can't
        express (nodepool limits, features outside the tensor encoding)
        fall back to first_n_binary — the reference's O(log N) bisection,
        not the old O(N) largest-first scan."""
        if not self.force_oracle:
            from karpenter_tpu.controllers.disruption.sweep import (
                SweepUnsupported,
                sweep_first_n,
            )

            try:
                return sweep_first_n(self, candidates)
            except SweepUnsupported:
                pass
        return self.first_n_binary(candidates)

    def first_n_sets(self, candidates: list[Candidate]) -> Command:
        """Rung 1 (round 6): bounded exhaustive search over ARBITRARY
        removal sets — proposal rounds, one batched device dispatch each,
        winner materialized through compute_consolidation with the
        largest feasible prefix as a backstop (disruption/setsweep.py).
        Shapes the set kernel can't express fall to the prefix rungs."""
        if not self.force_oracle:
            from karpenter_tpu.controllers.disruption.setsweep import (
                sweep_sets,
            )
            from karpenter_tpu.controllers.disruption.sweep import (
                SweepUnsupported,
            )

            try:
                return sweep_sets(self, candidates)
            except SweepUnsupported:
                pass
        return self.first_n_batched(candidates)


class SingleNodeConsolidation(ConsolidationBase):
    """singlenodeconsolidation.go:56: per-candidate simulation, nodepool
    round-robin ordering so one big pool can't starve the others.

    Round 5: the per-candidate simulations are INDEPENDENT — with
    sweep="batched" (default) one device call computes every candidate's
    removal feasibility as a lane of the delta-state sweep
    (disruption/sweep.py singleton mode); the sequential walk then only
    runs the full exact simulation on candidates whose lane came back
    feasible (an infeasible lane can only ever produce a no-op command,
    so skipping it is exact). Shapes the sweep can't express fall back to
    the reference's sequential scan."""

    def __init__(self, *args, sweep: str = "batched", **kwargs):
        super().__init__(*args, **kwargs)
        self.sweep = sweep

    def compute_commands(self) -> list[Command]:
        candidates = self.candidates()
        budgets = build_budget_mapping(self.kube, self.cluster, self.reason)
        # round-robin across nodepools (singlenodeconsolidation.go:139)
        by_pool: dict[str, list[Candidate]] = {}
        for c in candidates:
            by_pool.setdefault(c.nodepool_name, []).append(c)
        ordered: list[Candidate] = []
        pools = sorted(by_pool)
        i = 0
        while any(by_pool.values()):
            pool = pools[i % len(pools)]
            if by_pool[pool]:
                ordered.append(by_pool[pool].pop(0))
            i += 1
        feasible = None
        # force_oracle is the kernel kill-switch: never let the TPU sweep
        # drive skip decisions for an oracle-forced controller (matches
        # MultiNodeConsolidation.first_n_batched's guard)
        if self.sweep == "batched" and not self.force_oracle and len(ordered) > 1:
            from karpenter_tpu.controllers.disruption.sweep import (
                SweepUnsupported,
                singleton_feasibility,
            )

            try:
                feasible = singleton_feasibility(
                    self.kube, self.cluster, self.cloud, ordered, self.opts
                )
            except SweepUnsupported:
                feasible = None
        # single-node gets its OWN budget: the reference walks candidates
        # for up to 3 minutes (singlenodeconsolidation.go:31
        # SingleNodeConsolidationTimeoutDuration), three times the
        # multi-node bisection's 1-minute budget
        # (multinodeconsolidation.go:35) it used to borrow here
        deadline = (
            self.clock.now()
            + self.opts.singlenode_consolidation_timeout_seconds
        )
        for j, c in enumerate(ordered):
            if self.clock.now() > deadline:
                break
            if not budgets.can_disrupt(c.nodepool_name):
                continue
            if feasible is not None and not feasible[j]:
                continue  # lane says removal can't reschedule: no-op anyway
            cmd = self.compute_consolidation([c])
            if cmd.candidates:
                return [cmd]
        return []
