"""StaticDrift: replace drifted nodes owned by static (replica-count)
NodePools — the only disruption method allowed to touch static pools.

Reference /root/reference/pkg/controllers/disruption/staticdrift.go:35-117:
group candidates by nodepool, skip pools mid-scale-down, reserve node count
against the pool's `nodes` limit, and emit one replace-command per drifted
node whose replacement is a bare NodeClaimTemplate launch (no pods — the
static pool's capacity is workload-independent).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.controllers.disruption.helpers import (
    build_budget_mapping,
    build_candidates,
)
from karpenter_tpu.controllers.disruption.types import Candidate, Command
from karpenter_tpu.controllers.static import node_limit
from karpenter_tpu.options import Options
from karpenter_tpu.solver.nodes import NodeClaimTemplate

REASON_DRIFTED = "drifted"

_replacement_seq = [0]


class StaticDrift:
    """staticdrift.go:35 StaticDrift subreconciler."""

    reason = REASON_DRIFTED

    def __init__(
        self,
        kube,
        cluster,
        cloud_provider,
        clock,
        options: Optional[Options] = None,
        recorder=None,
        force_oracle: bool = False,
    ):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud_provider
        self.clock = clock
        self.opts = options or Options()
        self.recorder = recorder

    def should_disrupt(self, c: Candidate) -> bool:
        """staticdrift.go:51: static-owned and Drifted."""
        return c.owned_by_static_nodepool() and c.drifted()

    def compute_commands(self) -> list[Command]:
        candidates = build_candidates(
            self.kube, self.cluster, self.cloud, self.clock,
            self.should_disrupt, disruption_class="eventual",  # staticdrift.go:112
        )
        if not candidates:
            return []
        budgets = build_budget_mapping(self.kube, self.cluster, self.reason)
        by_pool: dict[str, list[Candidate]] = {}
        for c in candidates:
            by_pool.setdefault(c.nodepool_name, []).append(c)

        cmds: list[Command] = []
        for np_name, cands in by_pool.items():
            np = cands[0].node_pool
            allowed = budgets.allowed.get(np_name, 0)
            if allowed == 0:
                continue
            # staticdrift.go:76: don't replace while a scale-down is in
            # flight (more running+pending than desired replicas)
            active, _, pending = self.cluster.nodepool_state.node_counts(np_name)
            if active + pending > (np.replicas or 0):
                continue
            max_drifts = min(allowed, len(cands))
            # staticdrift.go:87: reserve replacements against the node limit
            granted = self.cluster.nodepool_state.reserve_node_count(
                np_name, node_limit(np), max_drifts
            )
            for c in cands[:granted]:
                nct = NodeClaimTemplate(np)
                replacement = nct.to_node_claim(
                    nct.requirements.copy(), InstanceTypes()
                )
                _replacement_seq[0] += 1
                replacement.metadata.name = (
                    f"{np_name}-staticdrift-{_replacement_seq[0]:05d}"
                )
                cmds.append(
                    Command(
                        reason=self.reason,
                        candidates=[c],
                        replacements=[replacement],
                        reserved_pool=np_name,
                        reserved_count=1,
                    )
                )
        return cmds
