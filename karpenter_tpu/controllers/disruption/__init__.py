"""Disruption (consolidation) subsystem — the second consumer of the solver.

Reference /root/reference/pkg/controllers/disruption/. The simulation
primitive (helpers.simulate_scheduling) routes through the HybridScheduler,
so consolidation decisions ride the TPU path whenever the problem encodes.
"""

from karpenter_tpu.controllers.disruption.consolidation import (
    DriftConsolidation,
    EmptinessConsolidation,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.controller import DisruptionController
from karpenter_tpu.controllers.disruption.helpers import (
    BudgetMapping,
    build_budget_mapping,
    build_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.disruption.queue import OrchestrationQueue, Validator
from karpenter_tpu.controllers.disruption.setsweep import (
    SetProposer,
    SetSweepContext,
    sweep_sets,
)
from karpenter_tpu.controllers.disruption.types import (
    Candidate,
    Command,
    DECISION_DELETE,
    DECISION_NOOP,
    DECISION_REPLACE,
    command_savings,
)

__all__ = [
    "BudgetMapping",
    "Candidate",
    "Command",
    "DECISION_DELETE",
    "DECISION_NOOP",
    "DECISION_REPLACE",
    "DisruptionController",
    "DriftConsolidation",
    "EmptinessConsolidation",
    "MultiNodeConsolidation",
    "OrchestrationQueue",
    "SetProposer",
    "SetSweepContext",
    "SingleNodeConsolidation",
    "Validator",
    "build_budget_mapping",
    "build_candidates",
    "command_savings",
    "simulate_scheduling",
    "sweep_sets",
]
