"""NodeOverlay: price/capacity patches over instance types.

Reference /root/reference/pkg/controllers/nodeoverlay/ (+ the NodeOverlay
v1alpha1 CRD and designs/node-overlay.md): operators declare overlays that
adjust instance-type prices (absolute or percentage) or inject extra
capacity for matching types; overlays evaluate in weight order, conflicts
are detected, and results land in a swap-on-write InstanceTypeStore the
overlay cloud-provider decorator reads.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.api.objects import NodeSelectorRequirement, ObjectMeta
from karpenter_tpu.cloudprovider.decorators import InstanceTypeStore
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.utils import resources as res


@dataclass
class NodeOverlay:
    """The NodeOverlay CRD (v1alpha1): a selector over instance types plus
    one patch."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # which instance types the overlay hits (reqs over type requirements)
    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    weight: int = 0
    # exactly one of:
    price: Optional[float] = None  # absolute price override
    price_adjustment: Optional[str] = None  # "+10%", "-5%", "+0.01", "-0.02"
    capacity: dict = field(default_factory=dict)  # extra capacity resources

    @property
    def name(self) -> str:
        return self.metadata.name

    def validate(self) -> Optional[str]:
        set_fields = [
            f
            for f, v in (
                ("price", self.price),
                ("priceAdjustment", self.price_adjustment),
                ("capacity", self.capacity or None),
            )
            if v is not None
        ]
        if len(set_fields) > 1:
            return f"conflicting overlay fields: {', '.join(set_fields)}"
        if not set_fields:
            return "overlay patches nothing"
        if self.price_adjustment is not None:
            raw = self.price_adjustment.strip()
            if not raw or raw[0] not in "+-":
                return "priceAdjustment must start with + or -"
            body = raw[1:-1] if raw.endswith("%") else raw[1:]
            try:
                float(body)
            except ValueError:
                return f"invalid priceAdjustment {raw!r}"
        return None

    def adjusted_price(self, price: float) -> float:
        if self.price is not None:
            return self.price
        raw = self.price_adjustment.strip()
        sign = 1.0 if raw[0] == "+" else -1.0
        if raw.endswith("%"):
            return max(0.0, price * (1.0 + sign * float(raw[1:-1]) / 100.0))
        return max(0.0, price + sign * float(raw[1:]))


class NodeOverlayController:
    """nodeoverlay/controller.go:69: re-evaluate overlays into the store
    whenever overlays or instance types change."""

    def __init__(self, kube, cloud_provider, store: InstanceTypeStore):
        self.kube = kube
        self.cloud = cloud_provider
        self.store = store

    def reconcile_all(self) -> dict[str, str]:
        """Returns overlay name -> validation error for bad overlays."""
        overlays = sorted(
            self.kube.list("NodeOverlay"), key=lambda o: (-o.weight, o.name)
        )
        problems: dict[str, str] = {}
        active: list[NodeOverlay] = []
        for o in overlays:
            err = o.validate()
            if err is not None:
                problems[o.name] = err
                continue
            active.append(o)
        for np in self.kube.list("NodePool"):
            base = self.cloud.get_instance_types(np)
            self.store.update(np.name, self._apply(active, base))
        return problems

    def _apply(self, overlays: list[NodeOverlay], its) -> InstanceTypes:
        if not overlays:
            return its
        out = InstanceTypes()
        for it in its:
            patched = it
            for o in overlays:
                reqs = Requirements.from_node_selector_requirements(o.requirements)
                if not it.requirements.is_compatible(reqs):
                    continue
                patched = copy.deepcopy(patched) if patched is it else patched
                if o.capacity:
                    patched.capacity = res.merge(patched.capacity, o.capacity)
                    # invalidate the memoized allocatable
                    patched._allocatable = None
                else:
                    for off in patched.offerings:
                        off.price = o.adjusted_price(off.price)
                # highest-weight overlay wins per field; later (lower-weight)
                # overlays of the same kind don't stack (controller.go:69
                # ordered evaluation + conflict rules)
                break
            out.append(patched)
        return out
