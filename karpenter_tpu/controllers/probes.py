"""Health/readiness probes + metrics endpoint: the operator's HTTP surface.

Reference /root/reference/pkg/operator/operator.go:183-221: the manager
serves /healthz and /readyz (readiness gated on the informers/CRDs being
synced) plus the Prometheus registry on the metrics port. This framework's
single-process operator starts the same three endpoints on a background
thread when `Options.probe_port` is set (port 0 picks a free one):

- /healthz  — liveness: the process serves requests.
- /readyz   — readiness: the cluster-state cache is synced with the store
  (the same barrier every controller takes before acting, cluster.go:118).
- /metrics  — the Prometheus-style exposition of karpenter_tpu.metrics.
- /debug/solves       — recent solve-trace summaries from the bounded
  telemetry ring (karpenter_tpu.tracing; docs/observability.md). Always
  on: the ring + phase histograms are the default-cost telemetry tier.
- /debug/solves/<id>  — the full phase waterfall of one trace; a wire
  correlation id returns BOTH the client- and server-side halves. An
  unknown (or garbage) id answers 404 with a JSON error body — the
  endpoint's content type never depends on whether the lookup hit.
- /debug/programs     — the compiled-program cost catalog (solver/aot.py
  aot_manifest.json): every AOT-prewarmed (entry x rung x relax) combo
  with bucket signature, compile seconds, and XLA cost/memory analysis
  (flops / bytes accessed / argument+output+temp bytes).

When constructed with enable_profiling=True (operator.go:183 --enable-
profiling gate) it additionally serves the pprof analogs from
karpenter_tpu.profiling — and flips the tracing detail gate, so traces
carry per-dispatch sub-spans (pod_xs/kernel/fetch) while the gate is up:

- /debug/pprof/profile?seconds=N — sampling CPU profile of every live
  thread, collapsed-stack format (add &top=1 for a pprof-top table).
  N is clamped to MAX_PROFILE_SECONDS; non-numeric or non-positive N
  answers 400 (a handler thread must never block on attacker-shaped
  query strings).
- /debug/pprof/heap — tracemalloc top allocation sites.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu import metrics, tracing

# hard ceiling on one /debug/pprof/profile sampling window: the handler
# thread blocks for the whole window, so the query string must not be able
# to park it for arbitrary time (operator.go:183's pprof has the same
# property via http server timeouts)
MAX_PROFILE_SECONDS = 60.0


class ProbeServer:
    def __init__(
        self,
        kube,
        cluster,
        port: int = 0,
        host: str = "127.0.0.1",
        enable_profiling: bool = False,
    ):
        self.kube = kube
        self.cluster = cluster
        self.enable_profiling = enable_profiling
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._detail_set = False  # we flipped the tracing detail gate

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> None:
        kube, cluster = self.kube, self.cluster
        profiling_on = self.enable_profiling
        # the pprof gate doubles as the per-span-detail gate: while it is
        # up, traces record each dispatch's pod_xs/kernel/fetch sub-spans
        if profiling_on and not tracing.detail_enabled():
            tracing.set_detail(True)
            self._detail_set = True

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, "ok")
                elif self.path == "/readyz":
                    try:
                        ready = cluster.synced(kube)
                    except Exception:
                        ready = False
                    self._reply(200 if ready else 503, "ok" if ready else "state not synced")
                elif self.path == "/metrics":
                    try:
                        body = metrics.REGISTRY.render()
                    except Exception as e:  # registry mutating mid-render
                        self._reply(503, f"metrics unavailable: {e}")
                        return
                    self._reply(200, body, ctype="text/plain; version=0.0.4")
                elif self.path == "/debug/solves":
                    # newest first; summaries only (spans via /<id>)
                    body = json.dumps(
                        [
                            t.to_dict(summary=True)
                            for t in reversed(tracing.RING.snapshot())
                        ]
                    )
                    self._reply(200, body, ctype="application/json")
                elif self.path.startswith("/debug/solves/"):
                    ident = self.path[len("/debug/solves/"):]
                    found = tracing.RING.find(ident)
                    if not found:
                        # a JSON 404 body for unknown AND garbage ids:
                        # a dashboard polling a rotated-out trace id must
                        # get machine-readable "gone", not a text/plain
                        # surprise (ISSUE 15 satellite)
                        self._reply(
                            404,
                            json.dumps(
                                {
                                    "error": "no trace with this id in the ring",
                                    "id": ident,
                                }
                            ),
                            ctype="application/json",
                        )
                        return
                    # a wire id matches the client- AND server-side halves
                    # of one logical trace; the waterfall is the spans
                    # ordered by t0 within each half
                    body = json.dumps(
                        {"id": ident, "traces": [t.to_dict() for t in found]}
                    )
                    self._reply(200, body, ctype="application/json")
                elif self.path == "/debug/programs":
                    # compiled-program cost catalog (solver/aot.py): reads
                    # the manifest only — never compiles in a handler
                    try:
                        from karpenter_tpu.solver import aot

                        body = json.dumps(aot.program_catalog())
                    except Exception as e:
                        self._reply(503, f"catalog unavailable: {e}")
                        return
                    self._reply(200, body, ctype="application/json")
                elif self.path.startswith("/debug/pprof/") and profiling_on:
                    from urllib.parse import parse_qs, urlparse

                    from karpenter_tpu import profiling

                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    if url.path == "/debug/pprof/profile":
                        try:
                            seconds = float(q.get("seconds", ["1"])[0])
                        except ValueError:
                            self._reply(400, "seconds must be a number")
                            return
                        if not (seconds > 0):  # also rejects NaN
                            self._reply(400, "seconds must be positive")
                            return
                        sampler = profiling.profile_cpu(
                            min(seconds, MAX_PROFILE_SECONDS)
                        )
                        body = (
                            sampler.render_top()
                            if q.get("top", ["0"])[0] == "1"
                            else sampler.render_collapsed()
                        )
                        self._reply(200, body)
                    elif url.path == "/debug/pprof/heap":
                        self._reply(200, profiling.heap_snapshot())
                    else:
                        self._reply(404, "unknown pprof endpoint")
                else:
                    self._reply(404, "not found")

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._detail_set:
            tracing.set_detail(False)
            self._detail_set = False
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
