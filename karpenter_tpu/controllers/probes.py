"""Health/readiness probes + metrics endpoint: the operator's HTTP surface.

Reference /root/reference/pkg/operator/operator.go:183-221: the manager
serves /healthz and /readyz (readiness gated on the informers/CRDs being
synced) plus the Prometheus registry on the metrics port. This framework's
single-process operator starts the same three endpoints on a background
thread when `Options.probe_port` is set (port 0 picks a free one):

- /healthz  — liveness: the process serves requests.
- /readyz   — readiness: the cluster-state cache is synced with the store
  (the same barrier every controller takes before acting, cluster.go:118).
- /metrics  — the Prometheus-style exposition of karpenter_tpu.metrics.

When constructed with enable_profiling=True (operator.go:183 --enable-
profiling gate) it additionally serves the pprof analogs from
karpenter_tpu.profiling:

- /debug/pprof/profile?seconds=N — sampling CPU profile of every live
  thread, collapsed-stack format (add &top=1 for a pprof-top table).
- /debug/pprof/heap — tracemalloc top allocation sites.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu import metrics


class ProbeServer:
    def __init__(
        self,
        kube,
        cluster,
        port: int = 0,
        host: str = "127.0.0.1",
        enable_profiling: bool = False,
    ):
        self.kube = kube
        self.cluster = cluster
        self.enable_profiling = enable_profiling
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> None:
        kube, cluster = self.kube, self.cluster
        profiling_on = self.enable_profiling

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, "ok")
                elif self.path == "/readyz":
                    try:
                        ready = cluster.synced(kube)
                    except Exception:
                        ready = False
                    self._reply(200 if ready else 503, "ok" if ready else "state not synced")
                elif self.path == "/metrics":
                    try:
                        body = metrics.REGISTRY.render()
                    except Exception as e:  # registry mutating mid-render
                        self._reply(503, f"metrics unavailable: {e}")
                        return
                    self._reply(200, body, ctype="text/plain; version=0.0.4")
                elif self.path.startswith("/debug/pprof/") and profiling_on:
                    from urllib.parse import parse_qs, urlparse

                    from karpenter_tpu import profiling

                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    if url.path == "/debug/pprof/profile":
                        try:
                            seconds = float(q.get("seconds", ["1"])[0])
                        except ValueError:
                            self._reply(400, "seconds must be a number")
                            return
                        if not (seconds > 0):  # also rejects NaN
                            self._reply(400, "seconds must be positive")
                            return
                        sampler = profiling.profile_cpu(min(seconds, 60.0))
                        body = (
                            sampler.render_top()
                            if q.get("top", ["0"])[0] == "1"
                            else sampler.render_collapsed()
                        )
                        self._reply(200, body)
                    elif url.path == "/debug/pprof/heap":
                        self._reply(200, profiling.heap_snapshot())
                    else:
                        self._reply(404, "unknown pprof endpoint")
                else:
                    self._reply(404, "not found")

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
