"""NodeClaim auxiliary controllers: disruption conditions, expiration,
garbage collection, pod events, consistency, hydration.

Reference /root/reference/pkg/controllers/nodeclaim/:
- disruption/consolidation.go:38 (Consolidatable after consolidateAfter of
  pod-event quiet), disruption/drift.go:50-183 (Drifted via provider +
  nodepool hash)
- expiration/controller.go:57-97 (expireAfter deletes)
- garbagecollection/controller.go:60-119 (cloud<->cluster reconciliation)
- podevents/controller.go:63-99 (lastPodEventTime stamping)
- consistency/controller.go:79-150 (invariant checks)
- hydration/controller.go:56-77 (field backfill)
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_CONSISTENT_STATE_FOUND,
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    COND_EMPTY,
    COND_INITIALIZED,
    NodeClaim,
    NodePool,
    PodPhase,
)
from karpenter_tpu.controllers.kube import DELETED, Conflict, NotFound, SimKube
from karpenter_tpu.controllers.state import Cluster, is_reschedulable
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu import metrics

NODEPOOL_HASH_VERSION = "v1"

CLAIMS_EXPIRED = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_expired_total", "NodeClaims deleted by expiration.", ("nodepool",)
)
CLAIMS_GARBAGE_COLLECTED = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_garbage_collected_total",
    "NodeClaims or instances removed by garbage collection.",
    ("direction",),
)


def nodepool_hash(np: NodePool) -> str:
    """Static-field drift hash (reference nodepool.go Hash): the fields of
    the template that force replacement when changed."""
    spec = np.template
    payload = {
        "labels": dict(sorted(spec.labels.items())),
        "annotations": dict(sorted(spec.annotations.items())),
        "taints": sorted(
            (t.key, t.value, str(t.effect)) for t in spec.taints
        ),
        "startup_taints": sorted(
            (t.key, t.value, str(t.effect)) for t in spec.startup_taints
        ),
        "node_class_ref": spec.node_class_ref,
        "expire_after": spec.expire_after_seconds,
        "tgp": spec.termination_grace_period_seconds,
    }
    return hashlib.sha256(json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


class NodeClaimDisruptionConditions:
    """nodeclaim/disruption: stamps Consolidatable / Drifted / Empty."""

    def __init__(self, kube: SimKube, cluster: Cluster, cloud, clock):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock

    def reconcile_all(self) -> None:
        nodepools = {np.name: np for np in self.kube.list("NodePool")}
        for claim in self.kube.list("NodeClaim"):
            self.reconcile(claim, nodepools)

    def reconcile(self, claim: NodeClaim, nodepools: dict) -> None:
        if claim.metadata.deletion_timestamp is not None:
            return
        np = nodepools.get(claim.nodepool_name)
        if np is None:
            return
        changed = False
        changed |= self._consolidatable(claim, np)
        changed |= self._drifted(claim, np)
        changed |= self._empty(claim)
        if changed:
            try:
                self.kube.update("NodeClaim", claim)
            except (Conflict, NotFound):
                pass

    def _consolidatable(self, claim: NodeClaim, np: NodePool) -> bool:
        """consolidation.go:38: quiet (no pod events) for consolidateAfter."""
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return False
        quiet_since = claim.status.last_pod_event_time or claim.metadata.creation_timestamp
        consolidatable = (
            self.clock.now() - quiet_since
            >= np.disruption.consolidate_after_seconds
        )
        want = "True" if consolidatable else "False"
        if claim.status.conditions.get(COND_CONSOLIDATABLE) != want:
            claim.status.conditions[COND_CONSOLIDATABLE] = want
            return True
        return False

    def _drifted(self, claim: NodeClaim, np: NodePool) -> bool:
        """drift.go:50 isDrifted: static-field hash drift, then
        requirements drift, then the provider verdict (cheap checks first,
        matching the reference's ordering to save provider calls)."""
        drifted = ""
        claim_hash = claim.metadata.annotations.get(
            well_known.NODEPOOL_HASH_ANNOTATION_KEY
        )
        claim_ver = claim.metadata.annotations.get(
            well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        )
        if (
            claim_hash is not None
            and claim_ver == NODEPOOL_HASH_VERSION
            and claim_hash != nodepool_hash(np)
        ):
            drifted = "NodePoolDrifted"
        if not drifted:
            drifted = self._requirements_drifted(claim, np)
        if not drifted:
            drifted = self.cloud.is_drifted(claim) or ""
        want = "True" if drifted else "False"
        if claim.status.conditions.get(COND_DRIFTED) != want:
            claim.status.conditions[COND_DRIFTED] = want
            return True
        return False

    @staticmethod
    def _requirements_drifted(claim: NodeClaim, np: NodePool) -> str:
        """drift.go:168-174 areRequirementsDrifted: every nodepool template
        requirement must be compatible with the claim's label set (the
        labels PopulateNodeClaimDetails resolved at launch) — a nodepool
        whose requirements changed out from under its nodes drifts them."""
        from karpenter_tpu.scheduling import Requirements

        if not claim.metadata.labels:
            return ""  # not yet populated (pre-launch) — nothing to diff
        pool_reqs = Requirements.from_node_selector_requirements(
            np.template.requirements
        )
        claim_reqs = Requirements.from_labels(claim.metadata.labels)
        if claim_reqs.compatible(pool_reqs) is not None:
            return "RequirementsDrifted"
        return ""

    def _empty(self, claim: NodeClaim) -> bool:
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return False
        node_name = claim.status.node_name
        pods = [
            p
            for p in self.cluster.pods_on(node_name)
            if is_reschedulable(p)
        ] if node_name else []
        want = "True" if not pods else "False"
        if claim.status.conditions.get(COND_EMPTY) != want:
            claim.status.conditions[COND_EMPTY] = want
            return True
        return False


class PodEvents:
    """nodeclaim/podevents: stamp lastPodEventTime on REAL pod events for
    the claim's node (podevents/controller.go:63-99 + the Register event
    filter at controller.go:104): a pod newly BOUND to the node, newly
    TERMINAL (Succeeded/Failed), or newly TERMINATING (deletionTimestamp
    set). Event-driven off the SimKube watch (round 5) — the former
    count-delta heuristic went quiet under equal-count churn (one pod
    leaves while another binds between reconcile ticks), wrongly letting
    Consolidatable fire on a busy node. A finalizer-less sim delete skips
    the terminating transition, so a DELETED event with a node name stamps
    too (it IS that transition, compressed). Daemonset-owned pods are
    ignored (controller.go:66) and stamps dedupe per claim within 10s
    (dedupeTimeout, controller.go:41-44)."""

    DEDUPE_SECONDS = 10.0

    def __init__(self, kube: SimKube, cluster: Cluster, clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        # pod uid -> (node_name, terminal, terminating): the "old object"
        # a controller-runtime UpdateFunc sees; SimKube watches carry only
        # the new state
        self._seen: dict[str, tuple[str, bool, bool]] = {}
        kube.subscribe(self._on_event)

    def reconcile_all(self) -> None:
        """Kept for callers that tick controllers in a loop: stamping is
        watch-driven, so a tick has nothing to poll."""

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind != "Pod":
            return
        pod = obj
        if pod.metadata.annotations.get("karpenter.sh/daemonset"):
            return
        node = pod.node_name or ""
        terminal = str(pod.phase) in ("Succeeded", "Failed") or pod.phase in (
            PodPhase.SUCCEEDED,
            PodPhase.FAILED,
        )
        # the sim marks eviction with pod.terminating (termination.py
        # _evict_locked); real deletes set deletion_timestamp — union both,
        # like termination.py's own is-terminating check
        terminating = (
            pod.metadata.deletion_timestamp is not None or pod.terminating
        )
        if event == DELETED:
            old = self._seen.pop(pod.uid, None)
            was_terminating = old is not None and old[2]
            if node and not was_terminating:
                self._stamp(node)
            return
        old = self._seen.get(pod.uid)
        self._seen[pod.uid] = (node, terminal, terminating)
        if not node:
            return
        bound = old is None or not old[0]
        went_terminal = terminal and (old is None or not old[1])
        went_terminating = terminating and (old is None or not old[2])
        if bound or went_terminal or went_terminating:
            self._stamp(node)

    def _stamp(self, node_name: str) -> None:
        # resolve node -> claim through the cluster index (one try_get)
        # instead of deep-copying every claim per pod event — pod churn is
        # the highest-frequency watch stream
        now = self.clock.now()
        sn = self.cluster.node_by_name(node_name)
        names: list[str]
        if sn is not None and sn.node_claim is not None:
            names = [sn.node_claim.name]
        else:
            # informer not caught up yet: fall back to the full scan
            names = [
                c.name
                for c in self.kube.list("NodeClaim")
                if c.status.node_name == node_name
            ]
        for name in names:
            claim = self.kube.try_get("NodeClaim", name)
            if claim is None or claim.status.node_name != node_name:
                continue
            last = claim.status.last_pod_event_time
            if last and now - last < self.DEDUPE_SECONDS:
                return
            claim.status.last_pod_event_time = now
            try:
                self.kube.update("NodeClaim", claim)
            except (Conflict, NotFound):
                pass
            return


class Expiration:
    """nodeclaim/expiration: delete claims older than expireAfter
    (controller.go:57)."""

    def __init__(self, kube: SimKube, clock, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.clock = clock
        self.recorder = recorder

    def reconcile_all(self) -> int:
        expired = 0
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                continue
            if claim.expire_after_seconds is None:
                continue
            age = self.clock.now() - claim.metadata.creation_timestamp
            if age < claim.expire_after_seconds:
                continue
            self.kube.delete("NodeClaim", claim.name)
            CLAIMS_EXPIRED.inc({"nodepool": claim.nodepool_name or ""})
            if self.recorder:
                self.recorder.publish(
                    Event(
                        "NodeClaim", claim.name, "Normal", "Expired",
                        f"expired after {age:.0f}s",
                    )
                )
            expired += 1
        return expired


class GarbageCollection:
    """nodeclaim/garbagecollection: both directions (controller.go:60) —
    cloud instances without claims are terminated; launched claims whose
    instances vanished are deleted."""

    def __init__(self, kube: SimKube, cloud, clock):
        self.kube = kube
        self.cloud = cloud
        self.clock = clock

    def reconcile(self) -> tuple[int, int]:
        claims = self.kube.list("NodeClaim")
        claim_pids = {
            c.status.provider_id for c in claims if c.status.provider_id
        }
        # direction 1: instances with no claim
        orphans = 0
        for instance in list(self.cloud.list()):
            pid = instance.status.provider_id
            if pid and pid not in claim_pids:
                try:
                    self.cloud.delete(instance)
                    orphans += 1
                    CLAIMS_GARBAGE_COLLECTED.inc({"direction": "instance"})
                except Exception:
                    pass
        # direction 2: launched claims whose instance vanished
        live_pids = {
            i.status.provider_id for i in self.cloud.list() if i.status.provider_id
        }
        lost = 0
        for claim in claims:
            pid = claim.status.provider_id
            if not pid or claim.metadata.deletion_timestamp is not None:
                continue
            if pid not in live_pids:
                self.kube.delete("NodeClaim", claim.name)
                lost += 1
                CLAIMS_GARBAGE_COLLECTED.inc({"direction": "nodeclaim"})
        return orphans, lost


class Consistency:
    """nodeclaim/consistency: periodic invariant checks (nodeshape.go):
    the node's shape must match what the claim promised."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> list[str]:
        problems = []
        for claim in self.kube.list("NodeClaim"):
            if not claim.status.provider_id:
                continue  # consistency/controller.go:89
            issues = self._check(claim)
            if issues is None:
                continue  # node missing/deleting: lifecycle+GC own that
            cond = claim.status.conditions.get(COND_CONSISTENT_STATE_FOUND)
            want = "False" if issues else "True"
            if cond != want:
                claim.status.conditions[COND_CONSISTENT_STATE_FOUND] = want
                try:
                    self.kube.update("NodeClaim", claim)
                except (Conflict, NotFound):
                    pass
            for issue in issues:
                problems.append(f"{claim.name}: {issue}")
                if self.recorder:
                    self.recorder.publish(
                        Event("NodeClaim", claim.name, "Warning", "FailedConsistencyCheck", issue)
                    )
        return problems

    def _check(self, claim: NodeClaim) -> Optional[list[str]]:
        """The NodeShape check (consistency/nodeshape.go:35-58): for every
        resource the claim REQUESTED, the launched node's capacity must be
        at least 90% of the expected (claim status) capacity. Returns all
        issues found, or None when the claim is exempt (deleting, not yet
        initialized, or its node is not singular/present — controller.go:105
        delegates those to the lifecycle/GC controllers)."""
        if claim.metadata.deletion_timestamp is not None:
            return None
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return None
        node = self.kube.try_get("Node", claim.status.node_name)
        if node is None:
            return None
        issues = []
        for name, requested in claim.resources_requests.items():
            expected = claim.status.capacity.get(name, 0)
            if not requested or not expected:
                continue
            got = node.capacity.get(name, 0)
            pct = got / expected
            if pct < 0.90:
                issues.append(
                    f"expected {expected} of resource {name}, but found "
                    f"{got} ({pct * 100:.1f}% of expected)"
                )
        return issues


class Hydration:
    """nodeclaim+node hydration (upgrade backfill): ensure objects carry
    the fields newer controllers expect. Mirrors BOTH reference hydration
    controllers: nodeclaim/hydration/controller.go:56-77 (the node-class
    label onto the NodeClaim) and node/hydration/controller.go:58-80 (the
    same label onto the claim's Node), plus the nodepool drift-hash
    annotations pre-hash-versioning claims lack."""

    def __init__(self, kube: SimKube):
        self.kube = kube

    def reconcile_all(self) -> None:
        nodepools = {np.name: np for np in self.kube.list("NodePool")}
        class_of_node: dict[str, str] = {}
        for claim in self.kube.list("NodeClaim"):
            np = nodepools.get(claim.nodepool_name)
            changed = False
            ann = claim.metadata.annotations
            if np is not None and well_known.NODEPOOL_HASH_ANNOTATION_KEY not in ann:
                ann[well_known.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool_hash(np)
                ann[well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = (
                    NODEPOOL_HASH_VERSION
                )
                changed = True
            # nodeclaim/hydration/controller.go:68: the node-class label
            labels = claim.metadata.labels
            if claim.node_class_ref and (
                labels.get(well_known.NODECLASS_LABEL_KEY) != claim.node_class_ref
            ):
                labels[well_known.NODECLASS_LABEL_KEY] = claim.node_class_ref
                changed = True
            if claim.status.node_name and claim.node_class_ref:
                class_of_node[claim.status.node_name] = claim.node_class_ref
            if changed:
                try:
                    self.kube.update("NodeClaim", claim)
                except (Conflict, NotFound):
                    pass
        # node/hydration/controller.go:74: same label onto the Node
        for node in self.kube.list("Node"):
            ref = class_of_node.get(node.name)
            if (
                not ref
                or node.metadata.labels.get(well_known.NODECLASS_LABEL_KEY) == ref
            ):
                continue
            node.metadata.labels[well_known.NODECLASS_LABEL_KEY] = ref
            try:
                self.kube.update("Node", node)
            except (Conflict, NotFound):
                pass
