"""NodeClaim auxiliary controllers: disruption conditions, expiration,
garbage collection, pod events, consistency, hydration.

Reference /root/reference/pkg/controllers/nodeclaim/:
- disruption/consolidation.go:38 (Consolidatable after consolidateAfter of
  pod-event quiet), disruption/drift.go:50-183 (Drifted via provider +
  nodepool hash)
- expiration/controller.go:57-97 (expireAfter deletes)
- garbagecollection/controller.go:60-119 (cloud<->cluster reconciliation)
- podevents/controller.go:63-99 (lastPodEventTime stamping)
- consistency/controller.go:79-150 (invariant checks)
- hydration/controller.go:56-77 (field backfill)
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_CONSISTENT_STATE_FOUND,
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    COND_EMPTY,
    COND_INITIALIZED,
    NodeClaim,
    NodePool,
)
from karpenter_tpu.controllers.kube import Conflict, NotFound, SimKube
from karpenter_tpu.controllers.state import Cluster, is_reschedulable
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu import metrics

NODEPOOL_HASH_VERSION = "v1"

CLAIMS_EXPIRED = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_expired_total", "NodeClaims deleted by expiration.", ("nodepool",)
)
CLAIMS_GARBAGE_COLLECTED = metrics.REGISTRY.counter(
    "karpenter_nodeclaims_garbage_collected_total",
    "NodeClaims or instances removed by garbage collection.",
    ("direction",),
)


def nodepool_hash(np: NodePool) -> str:
    """Static-field drift hash (reference nodepool.go Hash): the fields of
    the template that force replacement when changed."""
    spec = np.template
    payload = {
        "labels": dict(sorted(spec.labels.items())),
        "annotations": dict(sorted(spec.annotations.items())),
        "taints": sorted(
            (t.key, t.value, str(t.effect)) for t in spec.taints
        ),
        "startup_taints": sorted(
            (t.key, t.value, str(t.effect)) for t in spec.startup_taints
        ),
        "node_class_ref": spec.node_class_ref,
        "expire_after": spec.expire_after_seconds,
        "tgp": spec.termination_grace_period_seconds,
    }
    return hashlib.sha256(json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


class NodeClaimDisruptionConditions:
    """nodeclaim/disruption: stamps Consolidatable / Drifted / Empty."""

    def __init__(self, kube: SimKube, cluster: Cluster, cloud, clock):
        self.kube = kube
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock

    def reconcile_all(self) -> None:
        nodepools = {np.name: np for np in self.kube.list("NodePool")}
        for claim in self.kube.list("NodeClaim"):
            self.reconcile(claim, nodepools)

    def reconcile(self, claim: NodeClaim, nodepools: dict) -> None:
        if claim.metadata.deletion_timestamp is not None:
            return
        np = nodepools.get(claim.nodepool_name)
        if np is None:
            return
        changed = False
        changed |= self._consolidatable(claim, np)
        changed |= self._drifted(claim, np)
        changed |= self._empty(claim)
        if changed:
            try:
                self.kube.update("NodeClaim", claim)
            except (Conflict, NotFound):
                pass

    def _consolidatable(self, claim: NodeClaim, np: NodePool) -> bool:
        """consolidation.go:38: quiet (no pod events) for consolidateAfter."""
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return False
        quiet_since = claim.status.last_pod_event_time or claim.metadata.creation_timestamp
        consolidatable = (
            self.clock.now() - quiet_since
            >= np.disruption.consolidate_after_seconds
        )
        want = "True" if consolidatable else "False"
        if claim.status.conditions.get(COND_CONSOLIDATABLE) != want:
            claim.status.conditions[COND_CONSOLIDATABLE] = want
            return True
        return False

    def _drifted(self, claim: NodeClaim, np: NodePool) -> bool:
        """drift.go:50 isDrifted: static-field hash drift, then
        requirements drift, then the provider verdict (cheap checks first,
        matching the reference's ordering to save provider calls)."""
        drifted = ""
        claim_hash = claim.metadata.annotations.get(
            well_known.NODEPOOL_HASH_ANNOTATION_KEY
        )
        claim_ver = claim.metadata.annotations.get(
            well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY
        )
        if (
            claim_hash is not None
            and claim_ver == NODEPOOL_HASH_VERSION
            and claim_hash != nodepool_hash(np)
        ):
            drifted = "NodePoolDrifted"
        if not drifted:
            drifted = self._requirements_drifted(claim, np)
        if not drifted:
            drifted = self.cloud.is_drifted(claim) or ""
        want = "True" if drifted else "False"
        if claim.status.conditions.get(COND_DRIFTED) != want:
            claim.status.conditions[COND_DRIFTED] = want
            return True
        return False

    @staticmethod
    def _requirements_drifted(claim: NodeClaim, np: NodePool) -> str:
        """drift.go:168-174 areRequirementsDrifted: every nodepool template
        requirement must be compatible with the claim's label set (the
        labels PopulateNodeClaimDetails resolved at launch) — a nodepool
        whose requirements changed out from under its nodes drifts them."""
        from karpenter_tpu.scheduling import Requirements

        if not claim.metadata.labels:
            return ""  # not yet populated (pre-launch) — nothing to diff
        pool_reqs = Requirements.from_node_selector_requirements(
            np.template.requirements
        )
        claim_reqs = Requirements.from_labels(claim.metadata.labels)
        if claim_reqs.compatible(pool_reqs) is not None:
            return "RequirementsDrifted"
        return ""

    def _empty(self, claim: NodeClaim) -> bool:
        if claim.status.conditions.get(COND_INITIALIZED) != "True":
            return False
        node_name = claim.status.node_name
        pods = [
            p
            for p in self.cluster.pods_on(node_name)
            if is_reschedulable(p)
        ] if node_name else []
        want = "True" if not pods else "False"
        if claim.status.conditions.get(COND_EMPTY) != want:
            claim.status.conditions[COND_EMPTY] = want
            return True
        return False


class PodEvents:
    """nodeclaim/podevents: stamp lastPodEventTime whenever a pod binds to
    or leaves the claim's node (controller.go:63)."""

    def __init__(self, kube: SimKube, cluster: Cluster, clock):
        self.kube = kube
        self.cluster = cluster
        self.clock = clock
        self._last_counts: dict[str, int] = {}

    def reconcile_all(self) -> None:
        for claim in self.kube.list("NodeClaim"):
            node_name = claim.status.node_name
            if not node_name:
                continue
            n = len(self.cluster.pods_on(node_name))
            if self._last_counts.get(claim.name) != n:
                self._last_counts[claim.name] = n
                claim.status.last_pod_event_time = self.clock.now()
                try:
                    self.kube.update("NodeClaim", claim)
                except (Conflict, NotFound):
                    pass


class Expiration:
    """nodeclaim/expiration: delete claims older than expireAfter
    (controller.go:57)."""

    def __init__(self, kube: SimKube, clock, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.clock = clock
        self.recorder = recorder

    def reconcile_all(self) -> int:
        expired = 0
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                continue
            if claim.expire_after_seconds is None:
                continue
            age = self.clock.now() - claim.metadata.creation_timestamp
            if age < claim.expire_after_seconds:
                continue
            self.kube.delete("NodeClaim", claim.name)
            CLAIMS_EXPIRED.inc({"nodepool": claim.nodepool_name or ""})
            if self.recorder:
                self.recorder.publish(
                    Event(
                        "NodeClaim", claim.name, "Normal", "Expired",
                        f"expired after {age:.0f}s",
                    )
                )
            expired += 1
        return expired


class GarbageCollection:
    """nodeclaim/garbagecollection: both directions (controller.go:60) —
    cloud instances without claims are terminated; launched claims whose
    instances vanished are deleted."""

    def __init__(self, kube: SimKube, cloud, clock):
        self.kube = kube
        self.cloud = cloud
        self.clock = clock

    def reconcile(self) -> tuple[int, int]:
        claims = self.kube.list("NodeClaim")
        claim_pids = {
            c.status.provider_id for c in claims if c.status.provider_id
        }
        # direction 1: instances with no claim
        orphans = 0
        for instance in list(self.cloud.list()):
            pid = instance.status.provider_id
            if pid and pid not in claim_pids:
                try:
                    self.cloud.delete(instance)
                    orphans += 1
                    CLAIMS_GARBAGE_COLLECTED.inc({"direction": "instance"})
                except Exception:
                    pass
        # direction 2: launched claims whose instance vanished
        live_pids = {
            i.status.provider_id for i in self.cloud.list() if i.status.provider_id
        }
        lost = 0
        for claim in claims:
            pid = claim.status.provider_id
            if not pid or claim.metadata.deletion_timestamp is not None:
                continue
            if pid not in live_pids:
                self.kube.delete("NodeClaim", claim.name)
                lost += 1
                CLAIMS_GARBAGE_COLLECTED.inc({"direction": "nodeclaim"})
        return orphans, lost


class Consistency:
    """nodeclaim/consistency: periodic invariant checks (nodeshape.go):
    the node's shape must match what the claim promised."""

    def __init__(self, kube: SimKube, cluster: Cluster, recorder: Optional[Recorder] = None):
        self.kube = kube
        self.cluster = cluster
        self.recorder = recorder

    def reconcile_all(self) -> list[str]:
        problems = []
        for claim in self.kube.list("NodeClaim"):
            if claim.status.conditions.get(COND_INITIALIZED) != "True":
                continue
            issue = self._check(claim)
            want = "False" if issue else "True"
            if claim.status.conditions.get(COND_CONSISTENT_STATE_FOUND) != want:
                claim.status.conditions[COND_CONSISTENT_STATE_FOUND] = want
                try:
                    self.kube.update("NodeClaim", claim)
                except (Conflict, NotFound):
                    pass
            if issue:
                problems.append(f"{claim.name}: {issue}")
                if self.recorder:
                    self.recorder.publish(
                        Event("NodeClaim", claim.name, "Warning", "FailedConsistencyCheck", issue)
                    )
        return problems

    def _check(self, claim: NodeClaim) -> Optional[str]:
        node = self.kube.try_get("Node", claim.status.node_name)
        if node is None:
            return "node missing for initialized claim"
        for name, want in claim.status.capacity.items():
            got = node.capacity.get(name, 0)
            if got < want:
                return (
                    f"node capacity {name} {got} below claim capacity {want}"
                )
        return None


class Hydration:
    """nodeclaim+node hydration (upgrade backfill): ensure objects carry the
    fields newer controllers expect — here the nodepool hash-version
    annotation and the nodepool label on nodes."""

    def __init__(self, kube: SimKube):
        self.kube = kube

    def reconcile_all(self) -> None:
        nodepools = {np.name: np for np in self.kube.list("NodePool")}
        for claim in self.kube.list("NodeClaim"):
            np = nodepools.get(claim.nodepool_name)
            if np is None:
                continue
            ann = claim.metadata.annotations
            if well_known.NODEPOOL_HASH_ANNOTATION_KEY not in ann:
                ann[well_known.NODEPOOL_HASH_ANNOTATION_KEY] = nodepool_hash(np)
                ann[well_known.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = (
                    NODEPOOL_HASH_VERSION
                )
                try:
                    self.kube.update("NodeClaim", claim)
                except (Conflict, NotFound):
                    pass
