"""The operator runtime: assembles the store, state cache, provider, and
controllers, and drives them as one simulation-friendly loop.

Reference /root/reference/pkg/operator/operator.go:117-249 + kwok/main.go:29-51.
The reference runs controllers on a manager with watches and leader election;
here the same controllers are driven by an explicit `step()` tick, which is
what the tests and the benchmark harness call (the reference's envtest suites
drive reconcilers manually the same way — SURVEY.md §4.1)."""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api.objects import Pod
from karpenter_tpu.cloudprovider.decorators import (
    InstanceTypeStore,
    MetricsCloudProvider,
    OverlayCloudProvider,
)
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.disruption import DisruptionController
from karpenter_tpu.controllers.kube import FakeClock, SimKube
from karpenter_tpu.controllers.metrics_controllers import (
    NodeMetricsController,
    NodePoolMetricsController,
    PodMetricsController,
)
from karpenter_tpu.controllers.nodeoverlay import NodeOverlayController
from karpenter_tpu.controllers.static import StaticDeprovisioning, StaticProvisioning
from karpenter_tpu.controllers.lifecycle import NodeClaimLifecycle
from karpenter_tpu.controllers.nodeclaim_aux import (
    Consistency,
    Expiration,
    GarbageCollection,
    Hydration,
    NodeClaimDisruptionConditions,
    PodEvents,
)
from karpenter_tpu.controllers.nodepool_aux import (
    NodeHealth,
    NodePoolCounter,
    NodePoolHash,
    NodePoolReadiness,
    NodePoolValidation,
    RegistrationHealth,
)
from karpenter_tpu.controllers.provisioning import Provisioner
from karpenter_tpu.controllers.state import Cluster, is_provisionable, wire_informers
from karpenter_tpu.controllers.termination import NodeTermination
from karpenter_tpu.events import Recorder
from karpenter_tpu.options import Options


class Operator:
    """NewOperator + WithControllers + Start, in simulation time."""

    def __init__(
        self,
        clock=None,
        cloud_provider=None,
        options: Optional[Options] = None,
        force_oracle: bool = False,
        solver=None,
    ):
        self.clock = clock or FakeClock()
        self.opts = options or Options()
        # operator startup is a sanctioned persistent-cache config site
        # (with the solver package import and SolverServer.start): the
        # import-time call latches the env seen THEN, and a main() that
        # sets KARPENTER_COMPILATION_CACHE_DIR after importing us must
        # still be honored — ensure re-applies on env change. A restarted
        # operator must not pay a cold compile inside a Solve
        # (provisioner.go:366 1-min budget).
        from karpenter_tpu.jaxsetup import ensure_compilation_cache

        ensure_compilation_cache()
        # structured logging (reference operator/logging/logging.go): one
        # JSON-lines root, level from options, timestamps from the sim clock
        from karpenter_tpu import logging as klog

        klog.root.set_level(self.opts.log_level)
        klog.root.set_clock(self.clock)
        self.kube = SimKube(self.clock)
        self.cluster = Cluster(self.clock)
        wire_informers(self.kube, self.cluster)
        self.recorder = Recorder(self.clock)
        raw_cloud = cloud_provider or KwokCloudProvider(self.kube, self.clock)
        # decorator stack (kwok/main.go:31-38): overlay over metrics over raw
        self.raw_cloud = raw_cloud
        self.overlay_store = InstanceTypeStore()
        decorated = MetricsCloudProvider(raw_cloud)
        if self.opts.feature_gates.node_overlay:
            decorated = OverlayCloudProvider(decorated, self.overlay_store)
        self.cloud = decorated
        self.provisioner = Provisioner(
            self.kube,
            self.cluster,
            self.cloud,
            self.clock,
            self.opts,
            self.recorder,
            force_oracle=force_oracle,
            # optional ResilientSolver: route solves through the sidecar
            # boundary with the in-process ladder as the floor
            solver=solver,
        )
        self.lifecycle = NodeClaimLifecycle(
            self.kube, self.cluster, self.cloud, self.clock, self.opts, self.recorder
        )
        self.termination = NodeTermination(
            self.kube, self.cluster, self.cloud, self.clock, self.recorder,
            workers=self.opts.termination_workers,
        )
        self.disruption = DisruptionController(
            self.kube,
            self.cluster,
            self.cloud,
            self.provisioner,
            self.clock,
            self.opts,
            self.recorder,
            force_oracle=force_oracle,
        )
        # aux controllers (reference pkg/controllers/controllers.go:66 registry)
        self.nodepool_hash = NodePoolHash(self.kube)
        self.nodepool_counter = NodePoolCounter(self.kube, self.cluster)
        self.nodepool_readiness = NodePoolReadiness(self.kube, self.cloud)
        self.nodepool_validation = NodePoolValidation(self.kube, self.recorder)
        self.registration_health = RegistrationHealth(self.kube)
        self.lifecycle.registration_health = self.registration_health
        self.hydration = Hydration(self.kube)
        self.pod_events = PodEvents(self.kube, self.cluster, self.clock)
        self.claim_conditions = NodeClaimDisruptionConditions(
            self.kube, self.cluster, self.cloud, self.clock
        )
        self.expiration = Expiration(self.kube, self.clock, self.recorder)
        self.garbage_collection = GarbageCollection(self.kube, self.cloud, self.clock)
        self.consistency = Consistency(self.kube, self.cluster, self.recorder)
        self.node_health = (
            NodeHealth(self.kube, self.cluster, self.cloud, self.clock, self.recorder)
            if self.opts.feature_gates.node_repair
            else None
        )
        # static-capacity pools provision via their own loop (controllers.go:139
        # gate; provisioning.py excludes replicas!=None pools for this reason)
        self.static_provisioning = (
            StaticProvisioning(self.kube, self.cluster, self.recorder)
            if self.opts.feature_gates.static_capacity
            else None
        )
        self.static_deprovisioning = (
            StaticDeprovisioning(self.kube, self.cluster, self.recorder)
            if self.opts.feature_gates.static_capacity
            else None
        )
        self.node_overlay = (
            NodeOverlayController(self.kube, self.raw_cloud, self.overlay_store)
            if self.opts.feature_gates.node_overlay
            else None
        )
        # HTTP probe surface (operator.go:183-221), opt-in via probe_port
        self.probes = None
        if self.opts.probe_port is not None:
            from karpenter_tpu.controllers.probes import ProbeServer

            self.probes = ProbeServer(
                self.kube,
                self.cluster,
                port=self.opts.probe_port,
                enable_profiling=self.opts.enable_profiling,
            )
            self.probes.start()
        # leader election (operator.go:157-182): configured via lease_path;
        # a standby keeps its informers/cache warm but acts on nothing
        self.elector = None
        if self.opts.leader_elect_lease_path:
            from karpenter_tpu.leaderelection import LeaderElector

            # lease timestamps are persisted and compared ACROSS process
            # lifetimes, so only a wall clock is valid there — RealClock is
            # monotonic (epoch = host boot) and would wedge every candidate
            # in standby after a reboot. The sim's FakeClock is fine: tests
            # control it explicitly and share it between candidates.
            self.elector = LeaderElector(
                self.opts.leader_elect_lease_path,
                lease_duration=self.opts.leader_elect_lease_seconds,
                renew_period=self.opts.leader_elect_renew_seconds,
                clock=self.clock if isinstance(self.clock, FakeClock) else None,
            )
        self.node_metrics = NodeMetricsController(self.cluster)
        self.nodepool_metrics = NodePoolMetricsController(self.kube)
        self.pod_metrics = PodMetricsController(self.kube, self.cluster, self.clock)
        # pure observability: poll on an interval like the reference's
        # metrics controllers, not every reconcile round
        self._metrics_interval = 10.0
        self._metrics_last = -1e18

        # trigger controllers (provisioning/controller.go:44): watch events
        def triggers(event: str, kind: str, obj) -> None:
            if kind == "Pod" and event in ("added", "updated"):
                if isinstance(obj, Pod) and is_provisionable(obj):
                    self.provisioner.trigger_pod(obj)
            if kind == "Node" and event == "updated":
                if obj.metadata.deletion_timestamp is not None:
                    self.provisioner.trigger_node_deletion(obj.name)

        self.kube.subscribe(triggers)

    def stop(self) -> None:
        """Release process-level resources: the probe HTTP server's socket
        and thread, and the global logger's reference to this sim's clock.
        Like the reference's one-manager-per-process model, logging config
        (level) is process-global — two concurrent Operators share it."""
        if self.probes is not None:
            self.probes.stop()
            self.probes = None
        if self.elector is not None:
            self.elector.release()  # hand off without waiting out the lease
        from karpenter_tpu import logging as klog

        if klog.root._clock is self.clock:
            klog.root.set_clock(None)

    # -- loop -------------------------------------------------------------

    def step(self, advance_seconds: float = 1.0) -> None:
        """One control-plane tick: advance time, flush provider async work,
        run every controller once (informer updates flow synchronously via
        the store subscription)."""
        if isinstance(self.clock, FakeClock):
            self.clock.advance(advance_seconds)
        if self.elector is not None and not self.elector.ensure():
            return  # standby: informers stay warm via store subscriptions
        if hasattr(self.cloud, "reconcile"):
            self.cloud.reconcile()  # KWOK registration delays
        self.nodepool_hash.reconcile_all()
        self.nodepool_readiness.reconcile_all()
        self.nodepool_validation.reconcile_all()
        self.registration_health.reconcile_all()
        self.hydration.reconcile_all()
        self.lifecycle.reconcile_all()
        self.termination.reconcile_all()
        self.expiration.reconcile_all()
        self.garbage_collection.reconcile()
        self.pod_events.reconcile_all()
        self.claim_conditions.reconcile_all()
        self.nodepool_counter.reconcile_all()
        self.consistency.reconcile_all()
        if self.node_health is not None:
            self.node_health.reconcile_all()
        if self.node_overlay is not None:
            self.node_overlay.reconcile_all()
        if self.static_provisioning is not None:
            self.static_provisioning.reconcile_all()
        if self.static_deprovisioning is not None:
            self.static_deprovisioning.reconcile_all()
        if self.clock.now() - self._metrics_last >= self._metrics_interval:
            self._metrics_last = self.clock.now()
            self.node_metrics.reconcile_all()
            self.nodepool_metrics.reconcile_all()
            self.pod_metrics.reconcile_all()
        # the pod trigger controller requeues provisionable pods continuously
        # (provisioning/controller.go:60); without it a pod that failed or
        # awaits a node would never reopen the batch window
        for pod in self.kube.pending_pods():
            self.provisioner.trigger_pod(pod)
        self.provisioner.reconcile()
        if self.disruption is not None:
            self.disruption.reconcile()

    def run_until_settled(self, max_ticks: int = 60, advance_seconds: float = 2.0) -> int:
        """Step until no pending pods remain and all claims are initialized
        (or the tick budget runs out). Returns ticks used."""
        for tick in range(1, max_ticks + 1):
            self.step(advance_seconds)
            if self.settled():
                return tick
        return max_ticks

    def settled(self) -> bool:
        from karpenter_tpu.api.objects import COND_INITIALIZED

        if self.kube.pending_pods():
            return False
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                return False
            if claim.status.conditions.get(COND_INITIALIZED) != "True":
                return False
        for node in self.kube.list("Node"):
            if node.metadata.deletion_timestamp is not None:
                return False
        return True
