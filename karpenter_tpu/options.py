"""Operator options: the flat flag/env/feature-gate config system
(reference /root/reference/pkg/operator/options/options.go:67-216).

One dataclass carries every knob; `from_env` applies KARPENTER_* environment
fallbacks; feature gates parse from the same comma-separated string the
reference uses. Controllers receive Options explicitly (the reference
injects it through context.Context — explicit wiring is the Python idiom).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    """options.go:110 FeatureGates string:
    NodeRepair,ReservedCapacity,SpotToSpotConsolidation,NodeOverlay,StaticCapacity"""

    node_repair: bool = False
    reserved_capacity: bool = False
    spot_to_spot_consolidation: bool = False
    node_overlay: bool = False
    static_capacity: bool = False

    @classmethod
    def parse(cls, gates: str) -> "FeatureGates":
        out = cls()
        mapping = {
            "NodeRepair": "node_repair",
            "ReservedCapacity": "reserved_capacity",
            "SpotToSpotConsolidation": "spot_to_spot_consolidation",
            "NodeOverlay": "node_overlay",
            "StaticCapacity": "static_capacity",
        }
        for part in gates.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                name, val = part.split("=", 1)
                enabled = val.strip().lower() == "true"
            else:
                name, enabled = part, True
            attr = mapping.get(name.strip())
            if attr is not None:
                setattr(out, attr, enabled)
        return out


@dataclass
class Options:
    # batching (options.go:126-127)
    batch_idle_duration_seconds: float = 1.0
    batch_max_duration_seconds: float = 10.0
    # scheduling
    preference_policy: str = "Respect"  # Respect | Ignore
    min_values_policy: str = "Strict"  # Strict | BestEffort
    solve_timeout_seconds: float = 60.0  # provisioner.go:366
    tpu_claim_slot_div: int = 16  # SchedulerOptions.claim_slot_div
    tpu_min_pods: int = 768  # SchedulerOptions.tpu_min_pods (0 disables routing)
    # disruption
    disruption_poll_seconds: float = 10.0  # disruption/controller.go:69
    multinode_consolidation_timeout_seconds: float = 60.0
    # singlenodeconsolidation.go:31 SingleNodeConsolidationTimeoutDuration:
    # the per-candidate walk gets 3 minutes, distinct from the multi-node
    # bisection's 1-minute budget above
    singlenode_consolidation_timeout_seconds: float = 180.0
    # MultiNodeConsolidation search strategy ladder entry rung:
    # "sets" (arbitrary removal sets, disruption/setsweep.py) |
    # "batched" (prefix sweep) | "binary" (reference bisection);
    # unsupported shapes fall down the ladder automatically
    multinode_sweep_strategy: str = "sets"
    # termination reconciler pool width (termination/controller.go:58-60
    # scales 100->5000 in the reference; 1 keeps the sim deterministic)
    termination_workers: int = 1
    # lifecycle liveness TTLs (lifecycle/liveness.go)
    launch_ttl_seconds: float = 300.0
    registration_ttl_seconds: float = 900.0
    # client emulation
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    # observability
    log_level: str = "info"
    # start the /healthz /readyz /metrics HTTP surface on this port when
    # set (0 = pick a free port); None = no HTTP server (tests, benchmarks)
    probe_port: "int | None" = None
    enable_profiling: bool = False
    # HA: when lease_path is set, step() acts only while holding the lease
    # (operator.go:157-182 leader election); standbys keep informers warm
    leader_elect_lease_path: "str | None" = None
    leader_elect_lease_seconds: float = 15.0
    leader_elect_renew_seconds: float = 5.0
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    @classmethod
    def from_env(cls, env: dict | None = None) -> "Options":
        env = dict(os.environ if env is None else env)
        opts = cls()

        def f(key: str, cast, attr: str) -> None:
            raw = env.get(key)
            if raw is not None:
                try:
                    setattr(opts, attr, cast(raw))
                except ValueError:
                    pass

        f("KARPENTER_BATCH_IDLE_DURATION", float, "batch_idle_duration_seconds")
        f("KARPENTER_BATCH_MAX_DURATION", float, "batch_max_duration_seconds")
        f("KARPENTER_PREFERENCE_POLICY", str, "preference_policy")
        f("KARPENTER_MIN_VALUES_POLICY", str, "min_values_policy")
        f("KARPENTER_KUBE_CLIENT_QPS", int, "kube_client_qps")
        f("KARPENTER_KUBE_CLIENT_BURST", int, "kube_client_burst")
        f("KARPENTER_LOG_LEVEL", str, "log_level")
        f("KARPENTER_PROBE_PORT", int, "probe_port")
        f("KARPENTER_TERMINATION_WORKERS", int, "termination_workers")
        f("KARPENTER_TPU_CLAIM_SLOT_DIV", int, "tpu_claim_slot_div")
        f("KARPENTER_TPU_MIN_PODS", int, "tpu_min_pods")
        f(
            "KARPENTER_SINGLENODE_CONSOLIDATION_TIMEOUT",
            float,
            "singlenode_consolidation_timeout_seconds",
        )
        f("KARPENTER_MULTINODE_SWEEP_STRATEGY", str, "multinode_sweep_strategy")
        f("KARPENTER_LEADER_ELECT_LEASE_PATH", str, "leader_elect_lease_path")
        f("KARPENTER_LEADER_ELECT_LEASE_SECONDS", float, "leader_elect_lease_seconds")
        f("KARPENTER_LEADER_ELECT_RENEW_SECONDS", float, "leader_elect_renew_seconds")
        gates = env.get("KARPENTER_FEATURE_GATES")
        if gates:
            opts.feature_gates = FeatureGates.parse(gates)
        return opts
