"""karpenter_tpu — a TPU-native re-implementation of Karpenter's capabilities.

Kubernetes node autoscaling re-designed around a batched constraint-satisfaction
solver: pending-pods x instance-types x NodePools as dense feasibility tensors
evaluated on TPU via JAX/XLA (see `karpenter_tpu.ops` and `karpenter_tpu.solver`),
with a lean control plane (`karpenter_tpu.controllers`) orchestrating provisioning,
node lifecycle, and disruption against a pluggable cloud provider
(`karpenter_tpu.cloudprovider`).

Layer map (mirrors SURVEY.md §1 for the reference at /root/reference):
  api/            L0  CRD-equivalent domain objects (NodePool, NodeClaim, Pod, ...)
  scheduling/     L1  constraint algebra (Requirements, Taints, host ports)
  cloudprovider/  L2  provider SPI + fake + KWOK-style simulated provider
  controllers/    L3+L5  cluster state cache and control loops
  solver/         L4  the scheduling core: oracle FFD + batched TPU solver
  ops/            tensor encodings and JAX kernels backing the solver
  parallel/       device-mesh sharding of the solver (multi-chip)
  utils/          resource arithmetic, events, metrics, misc
"""

__version__ = "0.1.0"
