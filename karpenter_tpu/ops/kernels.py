"""Pure jittable kernels over the Reqs bitmask encoding.

These reproduce the reference's Requirement algebra exactly (see
karpenter_tpu/ops/encode.py for the encoding argument):

- ``intersect_nonempty``   == Requirement.HasIntersection per key
  (requirement.go:197), batched over broadcastable leading dims.
- ``compat``               == Requirements.Compatible (requirements.go:175):
  the defined-key rule plus Intersects with the NotIn/DoesNotExist
  tolerance (requirements.go:248).
- ``intersect``            == Requirements.Add auto-intersection
  (requirements.go:127 / requirement.go:158).
- ``distinct_value_counts`` powers SatisfiesMinValues (types.go:284).

Per-key reductions are matmuls against a one-hot [TW, K] matrix so XLA tiles
them onto the MXU; everything else is word-wise integer ops on the VPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.encode import Reqs
from karpenter_tpu.ops.vocab import Vocab


class VocabArrays(NamedTuple):
    """Device-resident static vocab tensors."""

    onehot: jax.Array  # [TW, K] f32
    word2key: jax.Array  # [TW] i32
    well_known: jax.Array  # [K] bool
    full_mask: jax.Array  # [TW] u32

    @classmethod
    def from_vocab(cls, vocab: Vocab) -> "VocabArrays":
        return cls(
            onehot=jnp.asarray(vocab.onehot),
            word2key=jnp.asarray(vocab.word2key),
            well_known=jnp.asarray(vocab.well_known_mask),
            full_mask=jnp.asarray(vocab.full_mask),
        )


def seg_any(word_flags: jax.Array, va: VocabArrays) -> jax.Array:
    """[..., TW] bool -> [..., K] bool: any set word per key."""
    return (word_flags.astype(jnp.float32) @ va.onehot) > 0


def seg_popcount(mask: jax.Array, va: VocabArrays) -> jax.Array:
    """[..., TW] u32 -> [..., K] i32: set-bit count per key."""
    pops = jax.lax.population_count(mask).astype(jnp.float32)
    return (pops @ va.onehot).astype(jnp.int32)


def _dne(r: Reqs, va: VocabArrays) -> jax.Array:
    """[..., K] operator()==DoesNotExist: concrete with empty allowed set."""
    return ~r.other & ~seg_any(r.mask != 0, va)


def intersect_nonempty(a: Reqs, b: Reqs, va: VocabArrays) -> jax.Array:
    """[..., K] bool — the per-key HasIntersection. Leading dims of a and b
    must broadcast (e.g. nodes [N, 1, ...] vs one pod [...])."""
    seg = seg_any((a.mask & b.mask) != 0, va)
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    other = a.other & b.other & (gt < lt)
    return seg | other


def _conflict(a: Reqs, b: Reqs, va: VocabArrays) -> tuple[jax.Array, jax.Array]:
    """Per-key conflict of shared defined keys, minus the NotIn/DoesNotExist
    tolerance (requirements.go:248). Returns (conflict[..., K], b_tol)."""
    nonempty = intersect_nonempty(a, b, va)
    a_tol = a.notin | _dne(a, va)
    b_tol = b.notin | _dne(b, va)
    conflict = a.defined & b.defined & ~nonempty & ~(a_tol & b_tol)
    return conflict, b_tol


def compat(
    a: Reqs, b: Reqs, va: VocabArrays, allow_undefined_well_known: bool
) -> jax.Array:
    """[...] bool — Requirements.Compatible(a=target/node, b=incoming/pod).

    allow_undefined_well_known mirrors passing AllowUndefinedWellKnownLabels
    (NodeClaim.CanAdd does; ExistingNode.CanAdd does not).
    """
    conflict, b_tol = _conflict(a, b, va)
    def_fail = b.defined & ~a.defined & ~b_tol
    if allow_undefined_well_known:
        def_fail = def_fail & ~va.well_known
    return ~jnp.any(conflict | def_fail, axis=-1)


def intersects_only(a: Reqs, b: Reqs, va: VocabArrays) -> jax.Array:
    """[...] bool — Requirements.Intersects without the defined-key rule
    (used by InstanceType requirement filtering, nodeclaim.go:376)."""
    conflict, _ = _conflict(a, b, va)
    return ~jnp.any(conflict, axis=-1)


def intersect(a: Reqs, b: Reqs, va: VocabArrays) -> Reqs:
    """Key-wise intersection of two requirement sets (Requirements.Add).

    The excluded set of a complement∧complement result is the union of the
    sides' excluded values refiltered against the *combined* bounds
    (requirement.go:158); `x.mask | x.exmask` is exactly "within x's own
    bounds" for every vocab value, so the refilter is two ANDs. A NotIn whose
    excluded values all fail the combined bounds thereby collapses to Exists
    (notin=False), which the tolerance rule in compat() relies on.
    """
    gt = jnp.maximum(a.gt, b.gt)
    lt = jnp.minimum(a.lt, b.lt)
    collapse = gt >= lt
    other = a.other & b.other & ~collapse
    keep = ~collapse[..., va.word2key]
    mask = jnp.where(keep, a.mask & b.mask, jnp.uint32(0))
    exmask = (a.exmask & (b.mask | b.exmask)) | (b.exmask & (a.mask | a.exmask))
    exmask = jnp.where(keep & other[..., va.word2key], exmask, jnp.uint32(0))
    return Reqs(
        mask=mask,
        exmask=exmask,
        other=other,
        notin=other & seg_any(exmask != 0, va),
        defined=a.defined | b.defined,
        gt=gt,
        lt=lt,
        minv=jnp.maximum(a.minv, b.minv),
    )


def distinct_value_counts(
    masks: jax.Array, alive: jax.Array, va: VocabArrays
) -> jax.Array:
    """[K] i32 — distinct allowed values per key across alive rows.

    masks: [I, TW] u32 (concrete requirement masks of instance types),
    alive: [I] bool. The union of per-type value sets, popcounted per key —
    the quantity SatisfiesMinValues compares against MinValues. Callers must
    pre-select the per-key source (`.values` semantics: concrete -> mask,
    complement -> exmask, undefined -> zero), as the solver's
    _min_values_ok does.
    """
    masked = jnp.where(alive[:, None], masks, jnp.uint32(0))
    union = jax.lax.reduce(masked, jnp.uint32(0), jax.lax.bitwise_or, (0,))
    return seg_popcount(union, va)
