"""Per-solve interning of label keys/values and exact resource scaling.

Label values, requirement keys, and instance-type counts vary per Solve; the
vocab is built once per Solve outside jit (SURVEY.md §7 "hard parts" #2) and
determines the static tensor shapes the kernels compile against. Value ids are
assigned in *sorted* order per key so argmin-by-id tie-breaks in the kernels
match the (determinized) oracle's sorted-iteration tie-breaks.

Resources are exact integer milli-quantities (karpenter_tpu.utils.quantity).
The TPU kernels use int32; to stay exact we divide every resource by the GCD
of all observed values of that resource. If the scaled range still overflows
int32 (pathological byte-granular requests on TB nodes) the problem is
rejected with UnsupportedProblem and the caller falls back to the oracle.
"""

from __future__ import annotations

import math
from typing import Iterable, Mapping, Optional

import numpy as np

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.scheduling.requirements import Requirement, Requirements

WORD_BITS = 32
# Safety bound: scaled resource values must leave headroom for one addition.
_MAX_SCALED = 1 << 30
# Reserved name prefix for phantom vocab keys added by shape bucketing
# (solver/buckets.py re-exports this): real label keys are DNS-ish and
# never start with a parenthesis, so collision is impossible.
PAD_KEY_PREFIX = "(bucket-pad-"


class UnsupportedProblem(Exception):
    """The problem can't be encoded exactly; use the oracle solver."""


class Vocab:
    """Key + per-key value interning for one Solve.

    The hostname key is handled *structurally* by the solver (a node IS its
    hostname domain) and is excluded here; requirements on it never enter the
    mask tensors.
    """

    def __init__(self) -> None:
        self._values: dict[str, set[str]] = {}
        self._finalized = False
        self.excluded_keys = frozenset({well_known.HOSTNAME_LABEL_KEY})

    # -- building --------------------------------------------------------

    def observe_requirements(self, reqs: Requirements) -> None:
        for r in reqs.values():
            self.observe_requirement(r)

    def observe_requirement(self, r: Requirement) -> None:
        if r.key in self.excluded_keys:
            return
        bucket = self._values.setdefault(r.key, set())
        bucket.update(r.values)

    def observe_labels(self, labels: Mapping[str, str]) -> None:
        for k, v in labels.items():
            k = well_known.NORMALIZED_LABELS.get(k, k)
            if k in self.excluded_keys:
                continue
            self._values.setdefault(k, set()).add(v)

    # -- finalizing ------------------------------------------------------

    def finalize(self, pad_words=None, pad_keys=None) -> None:
        """Freeze: assign key ids (sorted) and value ids (sorted per key),
        compute the flattened word layout.

        pad_words/pad_keys (optional, solver/buckets.py ladder callables)
        bucket the layout for compiled-shape stability: pad_words pads each
        key's word count, pad_keys the key count. Phantom word bits are
        semantically identical to the tail bits of a non-multiple-of-32
        value count (never in full_mask, never set by any row); phantom
        keys carry a reserved-prefix name, one zero word, no values, and
        stay defined=False in every encoded row — invisible to the
        requirement algebra (ops/kernels.py gates everything on defined)."""
        assert not self._finalized
        self.keys: list[str] = sorted(self._values)
        if pad_keys is not None:
            want = pad_keys(len(self.keys))
            for i in range(want - len(self.keys)):
                # ids are positional and phantom keys are appended after
                # the sorted real list, so real key ids never shift
                name = f"{PAD_KEY_PREFIX}{i})"
                self.keys.append(name)
                self._values[name] = set()
        self.key_index: dict[str, int] = {k: i for i, k in enumerate(self.keys)}
        self.values: list[list[str]] = [sorted(self._values[k]) for k in self.keys]
        self.value_index: list[dict[str, int]] = [
            {v: i for i, v in enumerate(vals)} for vals in self.values
        ]
        self.words_per_key: list[int] = [
            max(1, (len(vals) + WORD_BITS - 1) // WORD_BITS) for vals in self.values
        ]
        if pad_words is not None:
            self.words_per_key = [pad_words(w) for w in self.words_per_key]
        self.word_offset: list[int] = []
        off = 0
        for w in self.words_per_key:
            self.word_offset.append(off)
            off += w
        self.total_words = off
        self.num_keys = len(self.keys)
        # [TW] -> key id for segment reductions
        self.word2key = np.zeros(self.total_words, dtype=np.int32)
        for k, (o, w) in enumerate(zip(self.word_offset, self.words_per_key)):
            self.word2key[o : o + w] = k
        # one-hot [TW, K] for matmul-based per-key reductions (MXU-friendly)
        self.onehot = np.zeros((self.total_words, self.num_keys), dtype=np.float32)
        self.onehot[np.arange(self.total_words), self.word2key] = 1.0
        # full (Exists) mask: valid value bits set, padding bits clear
        self.full_mask = np.zeros(self.total_words, dtype=np.uint32)
        for k, vals in enumerate(self.values):
            for vid in range(len(vals)):
                self._set_bit(self.full_mask, k, vid)
        self.well_known_mask = np.array(
            [k in well_known.WELL_KNOWN_LABELS for k in self.keys], dtype=bool
        )
        self._finalized = True

    # -- lookups ---------------------------------------------------------

    def key_id(self, key: str) -> Optional[int]:
        return self.key_index.get(key)

    def value_id(self, key_id: int, value: str) -> Optional[int]:
        return self.value_index[key_id].get(value)

    def _set_bit(self, flat: np.ndarray, key_id: int, value_id: int) -> None:
        word = self.word_offset[key_id] + value_id // WORD_BITS
        flat[word] |= np.uint32(1 << (value_id % WORD_BITS))


class ResourceTable:
    """Fixed resource-dimension layout with exact per-resource GCD scaling."""

    def __init__(self) -> None:
        self._observed: dict[str, list[int]] = {}
        self._finalized = False

    def observe(self, rl: Mapping[str, int]) -> None:
        for name, v in rl.items():
            self._observed.setdefault(name, []).append(int(v))

    def finalize(self) -> None:
        assert not self._finalized
        self.names: list[str] = sorted(self._observed)
        self.index: dict[str, int] = {n: i for i, n in enumerate(self.names)}
        self.scale: list[int] = []
        for n in self.names:
            vals = [abs(v) for v in self._observed[n] if v != 0]
            g = 0
            for v in vals:
                g = math.gcd(g, v)
            g = g or 1
            self.scale.append(g)
            if vals and max(vals) // g >= _MAX_SCALED:
                raise UnsupportedProblem(
                    f"resource {n!r} range {max(vals)}/{g} overflows the exact "
                    "int32 encoding"
                )
        self.num_resources = len(self.names)
        self._finalized = True

    def encode(self, rl: Mapping[str, int]) -> np.ndarray:
        """ResourceList -> exact scaled int32 row. Values must be observed
        quantities (or sums thereof), so division is exact by construction."""
        row = np.zeros(self.num_resources, dtype=np.int64)
        for name, v in rl.items():
            i = self.index.get(name)
            if i is None:
                # A request for a resource no entity provides: encode the fact
                # by rejecting — callers observe() every relevant list first.
                raise UnsupportedProblem(f"resource {name!r} was never observed")
            q, r = divmod(int(v), self.scale[i])
            if r != 0:
                raise UnsupportedProblem(
                    f"resource {name!r} value {v} not divisible by scale {self.scale[i]}"
                )
            if q >= _MAX_SCALED:
                raise UnsupportedProblem(
                    f"resource {name!r} scaled value {q} overflows the exact "
                    "int32 encoding"
                )
            row[i] = q
        return row.astype(np.int32)

    def decode(self, row: np.ndarray) -> dict[str, int]:
        return {
            n: int(row[i]) * self.scale[i]
            for i, n in enumerate(self.names)
            if row[i] != 0
        }
