"""Tensor encodings and jittable kernels for the TPU solver.

The scheduling problem is re-expressed as dense tensors (SURVEY.md §7):
- `vocab`   — label-value interning + exact int32 resource scaling
- `encode`  — Requirements / InstanceTypes / Offerings -> bitmask tensors
- `kernels` — pure jax functions implementing the constraint algebra
  (intersection-nonempty, Compatible, intersect-update, instance-type
  filtering) batched over arbitrary leading dimensions
"""

from karpenter_tpu.ops.vocab import ResourceTable, UnsupportedProblem, Vocab
from karpenter_tpu.ops.encode import Reqs, encode_requirements, decode_row

__all__ = [
    "ResourceTable",
    "UnsupportedProblem",
    "Vocab",
    "Reqs",
    "encode_requirements",
    "decode_row",
]
