"""Requirements -> allowed-value bitmask tensors.

Encoding (per entity, per vocab key k):
- ``mask``  [TW] uint32 — allowed *vocab* values (bounds already folded in:
  a vocab value failing the requirement's own Gt/Lt bounds is cleared).
- ``other`` [K] bool — the requirement also allows values outside the vocab
  (i.e. it is a complement: NotIn / Exists / Gt / Lt).
- ``notin`` [K] bool — operator is NotIn (complement with explicit excluded
  values); needed for the NotIn/DoesNotExist tolerance rule in
  requirements.go:248 Intersects.
- ``exmask`` [TW] uint32 — for complements, the *explicitly excluded* vocab
  values that pass the requirement's own bounds. Intersections must refilter
  this set against the combined bounds (a NotIn whose excluded values all
  fail the combined Gt/Lt collapses to Exists, requirement.go:158); keeping
  it as a mask makes that an AND in the kernel and makes decode exact.
- ``defined`` [K] bool — the key is present in the requirement set. Undefined
  keys are stored as Exists (full mask + other) so intersections need no
  gating; the defined bits drive the Compatible() "custom labels must be
  defined" rule and shared-key conflict gating.
- ``gt``/``lt`` [K] int32 — integer bounds with ±sentinel defaults; combined
  bounds collapse (max(gt) >= min(lt)) kills the `other` bit exactly like
  requirement.go:158 Intersection returning DoesNotExist.
- ``minv`` [K] int32 — MinValues floor, -1 when absent.

With this layout every Requirement operation in the scheduler's hot path is a
word-wise AND plus per-key reductions — see karpenter_tpu.ops.kernels.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple

import numpy as np

from karpenter_tpu.api.objects import Operator
from karpenter_tpu.ops.vocab import WORD_BITS, UnsupportedProblem, Vocab
from karpenter_tpu.scheduling.requirements import (
    Requirement,
    Requirements,
    _within_bounds,
)

GT_NONE = np.int32(-(1 << 31))
LT_NONE = np.int32((1 << 31) - 1)


class Reqs(NamedTuple):
    """A batch of encoded requirement sets (a pytree of arrays; works with
    numpy for encoding and jax.numpy inside kernels)."""

    mask: np.ndarray  # [..., TW] uint32
    exmask: np.ndarray  # [..., TW] uint32
    other: np.ndarray  # [..., K] bool
    notin: np.ndarray  # [..., K] bool
    defined: np.ndarray  # [..., K] bool
    gt: np.ndarray  # [..., K] int32
    lt: np.ndarray  # [..., K] int32
    minv: np.ndarray  # [..., K] int32

    def row(self, i: int) -> "Reqs":
        return Reqs(*(a[i] for a in self))


def empty_reqs(vocab: Vocab, batch_shape: tuple[int, ...]) -> Reqs:
    """All-undefined (Exists-everything) batch."""
    tw, k = vocab.total_words, vocab.num_keys
    return Reqs(
        mask=np.broadcast_to(vocab.full_mask, batch_shape + (tw,)).copy(),
        exmask=np.zeros(batch_shape + (tw,), dtype=np.uint32),
        other=np.ones(batch_shape + (k,), dtype=bool),
        notin=np.zeros(batch_shape + (k,), dtype=bool),
        defined=np.zeros(batch_shape + (k,), dtype=bool),
        gt=np.full(batch_shape + (k,), GT_NONE, dtype=np.int32),
        lt=np.full(batch_shape + (k,), LT_NONE, dtype=np.int32),
        minv=np.full(batch_shape + (k,), -1, dtype=np.int32),
    )


def _encode_one(vocab: Vocab, out: Reqs, b: int, r: Requirement) -> None:
    kid = vocab.key_index.get(r.key)
    if kid is None:
        raise UnsupportedProblem(f"requirement key {r.key!r} not in vocab")
    off, words = vocab.word_offset[kid], vocab.words_per_key[kid]
    vals = vocab.values[kid]
    seg = np.zeros(words, dtype=np.uint32)
    exseg = np.zeros(words, dtype=np.uint32)

    def set_vid(target: np.ndarray, vid: int) -> None:
        target[vid // WORD_BITS] |= np.uint32(1 << (vid % WORD_BITS))

    if r.complement:
        # NotIn combined with Gt/Lt on the same key: the mask encoding drops
        # bound-failing excluded values, but the reference's minValues
        # distinct-value union keeps them (requirement.go Values()) — gate
        # rather than diverge
        if r.values and (r.greater_than is not None or r.less_than is not None):
            raise UnsupportedProblem(
                f"NotIn with Gt/Lt bounds on key {r.key!r} (minValues "
                "distinct-count would diverge from the reference)"
            )
        # NotIn excluded values must be in the vocab or the notin bit (and
        # with it the NotIn/DoesNotExist tolerance rule) silently flips
        for v in r.values:
            if v not in vocab.value_index[kid]:
                raise UnsupportedProblem(
                    f"excluded value {v!r} for key {r.key!r} not in vocab "
                    "(observe all requirement values before finalizing)"
                )
        # allowed = vocab \ excluded, bounds folded per value
        for vid, v in enumerate(vals):
            if not _within_bounds(v, r.greater_than, r.less_than):
                continue
            set_vid(exseg if v in r.values else seg, vid)
        # encode-time bound collapse (requirement.go:147)
        collapsed = (
            r.greater_than is not None
            and r.less_than is not None
            and r.greater_than >= r.less_than
        )
        out.other[b, kid] = not collapsed
        out.notin[b, kid] = bool(exseg.any()) and not collapsed
        if collapsed:
            seg[:] = 0
            exseg[:] = 0
        else:
            out.gt[b, kid] = GT_NONE if r.greater_than is None else r.greater_than
            out.lt[b, kid] = LT_NONE if r.less_than is None else r.less_than
    else:
        for v in r.values:
            vid = vocab.value_index[kid].get(v)
            if vid is None:
                raise UnsupportedProblem(
                    f"value {v!r} for key {r.key!r} not in vocab (observe all "
                    "requirement values before finalizing)"
                )
            set_vid(seg, vid)
        out.other[b, kid] = False
        out.notin[b, kid] = False
    out.mask[b, off : off + words] = seg
    out.exmask[b, off : off + words] = exseg
    out.defined[b, kid] = True
    out.minv[b, kid] = -1 if r.min_values is None else r.min_values


def encode_requirements(
    vocab: Vocab, batch: Iterable[Requirements], skip_keys: frozenset[str] = frozenset()
) -> Reqs:
    """Encode a list of Requirements sets into a Reqs batch. Keys in
    vocab.excluded_keys (hostname) and `skip_keys` are silently skipped —
    the solver handles them structurally."""
    batch = list(batch)
    out = empty_reqs(vocab, (len(batch),))
    skips = vocab.excluded_keys | skip_keys
    for b, reqs in enumerate(batch):
        for r in reqs.values():
            if r.key in skips:
                continue
            _encode_one(vocab, out, b, r)
    return out


def decode_row(vocab: Vocab, reqs: Reqs) -> Requirements:
    """Decode one encoded row back to Requirements.

    Exact for concrete (In / DoesNotExist) keys. Complement keys decode to
    NotIn over the exmask excluded set (vocab-relative) plus any Gt/Lt
    bounds — values never observed in this Solve are unrepresentable, which
    is semantically equivalent within the problem universe (every entity's
    values are in the vocab).
    """
    out = Requirements()
    for kid, key in enumerate(vocab.keys):
        if not reqs.defined[kid]:
            continue
        off, words = vocab.word_offset[kid], vocab.words_per_key[kid]
        vals = vocab.values[kid]

        def bit(flat: np.ndarray, vid: int) -> bool:
            return bool(
                flat[off + vid // WORD_BITS] >> np.uint32(vid % WORD_BITS)
                & np.uint32(1)
            )

        minv = None if reqs.minv[kid] < 0 else int(reqs.minv[kid])
        if reqs.other[kid]:
            excluded = {v for vid, v in enumerate(vals) if bit(reqs.exmask, vid)}
            r = Requirement._raw(
                key,
                True,
                excluded,
                None if reqs.gt[kid] == GT_NONE else int(reqs.gt[kid]),
                None if reqs.lt[kid] == LT_NONE else int(reqs.lt[kid]),
                minv,
            )
        else:
            allowed = [v for vid, v in enumerate(vals) if bit(reqs.mask, vid)]
            r = Requirement(key, Operator.IN, allowed, minv)
        out.add(r)
    return out
