"""Process-wide JAX setup: the persistent XLA compilation cache.

The reference's Solve budget is one minute (provisioner.go:366); a cold
XLA compile of the batched kernel is 30-70s at production shapes, so a
fresh operator process must not pay it inside a Solve. The persistent
compilation cache writes every compiled executable to disk keyed by
(HLO, compile options, platform); a restarted process deserializes in
milliseconds instead of recompiling (VERDICT r4 item #2).

Enabled on first solver use (TpuScheduler.solve, the sweep kernels, the
operator). Opt out with KARPENTER_COMPILATION_CACHE_DIR="" (empty);
override the location with the same variable.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "karpenter_tpu", "xla"
)
_configured = False
_env_seen: Optional[str] = None
_cache_dir: Optional[str] = None


def ensure_compilation_cache() -> Optional[str]:
    """Idempotently point JAX's persistent compilation cache at a durable
    directory. Returns the directory in use, or None when disabled.

    Safe to call before or after the first jax import/compile — JAX picks
    the config up on the next cache lookup. min_compile_time is floored at
    0 so even small programs (the per-solve helper jits) persist: a solve
    is a pipeline of ~10 compiled programs and every cold one counts
    against the Solve budget.

    The first call now happens at solver package import; a caller that
    sets KARPENTER_COMPILATION_CACHE_DIR *after* importing the package
    (the set-env-in-main pattern) is still honored — the config re-applies
    whenever the env value differs from the last one seen.
    """
    global _configured, _env_seen, _cache_dir
    raw = os.environ.get("KARPENTER_COMPILATION_CACHE_DIR")
    if _configured and raw == _env_seen:
        return _cache_dir
    _configured = True
    _env_seen = raw
    if raw == "":
        if _cache_dir is not None:
            # an earlier call enabled the cache: actually turn it off
            try:
                import jax

                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:
                pass
        _cache_dir = None
        return None
    cache_dir = raw or _DEFAULT_DIR
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _cache_dir = cache_dir
    except Exception:  # cache is an optimization; never fail a solve over it
        _cache_dir = None
    return _cache_dir
