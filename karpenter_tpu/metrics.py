"""Prometheus-style metrics registry (reference /root/reference/pkg/metrics/
metrics.go:32-99, constants.go:42-67, store.go:33-110).

Namespace `karpenter`, counters/gauges/histograms keyed by label tuples, a
`measure()` context manager mirroring the reference's defer-timer, and a
keyed gauge Store for metric garbage collection (a gauge family whose stale
series vanish when the backing object does). Exposition via render()."""

from __future__ import annotations

import threading

import math
import time
from contextlib import contextmanager
from typing import Iterable, Optional

NAMESPACE = "karpenter"

# reference pkg/metrics/constants.go:42 DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300,
]


class Metric:
    def __init__(self, name: str, help: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help
        self.label_names = label_names

    def _key(self, labels: dict[str, str]) -> tuple:
        return tuple(labels.get(k, "") for k in self.label_names)


class Counter(Metric):
    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, tuple(label_names))
        self.values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Optional[dict] = None, by: float = 1.0) -> None:
        k = self._key(labels or {})
        # controllers may run on worker pools (utils/workerpool.py); the
        # read-modify-write must not lose increments under preemption
        with self._lock:
            self.values[k] = self.values.get(k, 0.0) + by

    def value(self, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self.values.get(self._key(labels or {}), 0.0)


class Gauge(Metric):
    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, tuple(label_names))
        self.values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[dict] = None) -> None:
        with self._lock:
            self.values[self._key(labels or {})] = value

    def add(self, by: float, labels: Optional[dict] = None) -> None:
        k = self._key(labels or {})
        with self._lock:
            self.values[k] = self.values.get(k, 0.0) + by

    def value(self, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self.values.get(self._key(labels or {}), 0.0)

    def delete(self, labels: dict) -> None:
        with self._lock:
            self.values.pop(self._key(labels), None)


class Histogram(Metric):
    def __init__(self, name, help, label_names=(), buckets=None):
        super().__init__(name, help, tuple(label_names))
        self.buckets = list(buckets or DURATION_BUCKETS)
        self.counts: dict[tuple, list[int]] = {}
        self.sums: dict[tuple, float] = {}
        self.totals: dict[tuple, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[dict] = None) -> None:
        k = self._key(labels or {})
        with self._lock:
            if k not in self.counts:
                self.counts[k] = [0] * len(self.buckets)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[k][i] += 1
            self.sums[k] = self.sums.get(k, 0.0) + value
            self.totals[k] = self.totals.get(k, 0) + 1

    def count(self, labels: Optional[dict] = None) -> int:
        with self._lock:
            return self.totals.get(self._key(labels or {}), 0)

    def sum(self, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self.sums.get(self._key(labels or {}), 0.0)

    def snapshot(self) -> tuple[dict, dict, dict]:
        """Consistent (counts, sums, totals) copy for exposition: a
        /metrics scrape racing a worker-pool observe must not see a torn
        histogram (bucket/sum/count mismatch) or a dict mutated during
        iteration."""
        with self._lock:
            return (
                {k: list(v) for k, v in self.counts.items()},
                dict(self.sums),
                dict(self.totals),
            )

    @contextmanager
    def measure(self, labels: Optional[dict] = None):
        """metrics.Measure defer-timer (constants.go:63)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(time.monotonic() - t0, labels)


class Store:
    """Keyed gauge store for metric GC (reference store.go:33): update(key)
    replaces the series owned by that key; delete(key) removes them."""

    def __init__(self, gauge: Gauge):
        self.gauge = gauge
        self._owned: dict[str, list[dict]] = {}
        # controllers updating the same store may run on worker pools;
        # two racing update(key) calls must not interleave delete/set and
        # leak orphaned series. Lock order store -> gauge, never inverse:
        # the graftlint race tier witnesses this at runtime (racert, under
        # the faults suite) — a gauge-holding path calling back into a
        # Store would surface as a lock-order inversion there.
        self._lock = threading.Lock()

    def update(self, key: str, series: list[tuple[dict, float]]) -> None:
        with self._lock:
            self._delete_locked(key)
            owned = []
            for labels, value in series:
                self.gauge.set(value, labels)
                owned.append(labels)
            self._owned[key] = owned

    def delete(self, key: str) -> None:
        with self._lock:
            self._delete_locked(key)

    def _delete_locked(self, key: str) -> None:
        for labels in self._owned.pop(key, []):
            self.gauge.delete(labels)


def _escape_help(text: str) -> str:
    """Prometheus text-format HELP escaping: backslash and newline."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, quote,
    newline — an unescaped quote or newline in a label (a fallback reason,
    an error string) would corrupt the whole exposition."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Registry:
    def __init__(self):
        self.metrics: dict[str, Metric] = {}
        # registration mostly happens at import, but late registrations
        # (test fixtures, lazily-built controllers) can race a /metrics
        # scrape iterating the dict
        self._lock = threading.Lock()

    def counter(self, name, help, label_names=()) -> Counter:
        return self._register(Counter(name, help, label_names))

    def gauge(self, name, help, label_names=()) -> Gauge:
        return self._register(Gauge(name, help, label_names))

    def histogram(self, name, help, label_names=(), buckets=None) -> Histogram:
        return self._register(Histogram(name, help, label_names, buckets))

    def _register(self, m):
        with self._lock:
            existing = self.metrics.get(m.name)
            if existing is not None:
                return existing
            self.metrics[m.name] = m
            return m

    def render(self) -> str:
        """Prometheus text exposition."""
        lines = []
        with self._lock:
            snapshot = list(self.metrics.values())
        for m in snapshot:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            kind = (
                "counter"
                if isinstance(m, Counter)
                else "histogram"
                if isinstance(m, Histogram)
                else "gauge"
            )
            lines.append(f"# TYPE {m.name} {kind}")

            def fmt(key):
                if not m.label_names:
                    return ""
                pairs = ",".join(
                    f'{n}="{_escape_label(v)}"'
                    for n, v in zip(m.label_names, key)
                )
                return "{" + pairs + "}"

            if isinstance(m, Histogram):
                counts_s, sums_s, totals_s = m.snapshot()
                for k, counts in counts_s.items():
                    base = [
                        f'{n}="{_escape_label(v)}"'
                        for n, v in zip(m.label_names, k)
                    ]
                    for b, c in zip(m.buckets, counts):
                        pairs = ",".join(base + [f'le="{b}"'])
                        lines.append(f"{m.name}_bucket{{{pairs}}} {c}")
                    inf_pairs = ",".join(base + ['le="+Inf"'])
                    lines.append(f"{m.name}_bucket{{{inf_pairs}}} {totals_s[k]}")
                    lines.append(f"{m.name}_sum{fmt(k)} {sums_s[k]}")
                    lines.append(f"{m.name}_count{fmt(k)} {totals_s[k]}")
            else:
                with m._lock:
                    values_s = dict(m.values)
                for k, v in values_s.items():
                    lines.append(f"{m.name}{fmt(k)} {v}")
        return "\n".join(lines) + "\n"

    def reset(self):
        with self._lock:
            self.metrics.clear()


REGISTRY = Registry()


def reset() -> None:
    REGISTRY.reset()
