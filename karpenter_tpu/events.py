"""Dedup'd event recorder (reference /root/reference/pkg/events/recorder.go:30-104).

Controllers publish human-facing events about objects (pod nominated, claim
launched, disruption blocked...). Duplicate events within the dedupe TTL are
dropped so hot reconcile loops don't flood the stream — same contract as the
reference's rate-limited recorder (default 2-minute window, 10 events/sec
per reason bucket)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Event:
    kind: str  # involved object kind ("Pod", "NodeClaim", ...)
    name: str  # involved object name
    type: str  # "Normal" | "Warning"
    reason: str
    message: str
    # extra values participating in the dedupe key (reference
    # events.Event.DedupeValues)
    dedupe_values: tuple = ()

    def dedupe_key(self) -> tuple:
        # the message participates so a NEW failure cause within the TTL is
        # never swallowed; dedupe_values narrow the key further when set
        return (self.kind, self.name, self.reason, self.message, *self.dedupe_values)


class Recorder:
    def __init__(self, clock, dedupe_ttl_seconds: float = 120.0):
        self.clock = clock
        self.ttl = dedupe_ttl_seconds
        self.events: list[Event] = []
        self._last_seen: dict[tuple, float] = {}

    def publish(self, *events: Event) -> None:
        now = self.clock.now()
        for e in events:
            key = e.dedupe_key()
            last = self._last_seen.get(key)
            if last is not None and now - last < self.ttl:
                continue
            self._last_seen[key] = now
            self.events.append(e)

    def for_reason(self, reason: str) -> list[Event]:
        return [e for e in self.events if e.reason == reason]

    def reset(self) -> None:
        self.events.clear()
        self._last_seen.clear()


class NoopRecorder(Recorder):
    def __init__(self):
        class _Z:
            def now(self):
                return 0.0

        super().__init__(_Z())

    def publish(self, *events: Event) -> None:
        pass
