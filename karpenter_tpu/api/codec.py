"""JSON codec for the API objects: the wire form of a scheduling problem.

The solver service boundary (karpenter_tpu.solver.service) ships problems as
one JSON header plus flat array blobs; this module is the header side —
dataclass <-> jsonable dict, with enums by value and a class registry for
round-tripping. The reference's equivalent is the protobuf schema a
cgo->gRPC sidecar would use (SURVEY.md §7 M5)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from karpenter_tpu.api import objects as api
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    InstanceTypeOverhead,
    InstanceTypes,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling import Requirement, Requirements

_REGISTRY: dict[str, type] = {}


def _register(*classes):
    for c in classes:
        _REGISTRY[c.__name__] = c


_register(
    api.ObjectMeta,
    api.NodeSelectorRequirement,
    api.LabelSelectorRequirement,
    api.LabelSelector,
    api.Taint,
    api.Toleration,
    api.NodeSelectorTerm,
    api.PreferredSchedulingTerm,
    api.NodeAffinity,
    api.PodAffinityTerm,
    api.WeightedPodAffinityTerm,
    api.TopologySpreadConstraint,
    api.Container,
    api.Pod,
    api.Node,
    api.Budget,
    api.Disruption,
    api.NodeClaimTemplateSpec,
    api.NodePool,
    api.NodeClaimStatus,
    api.NodeClaim,
    api.PodDisruptionBudget,
    api.StorageClass,
    api.PersistentVolumeClaim,
    InstanceTypeOverhead,
)

_ENUMS = {
    e.__name__: e
    for e in (
        api.Operator,
        api.TaintEffect,
        api.WhenUnsatisfiable,
        api.NodeInclusionPolicy,
        api.PodPhase,
        api.ConsolidationPolicy,
    )
}

# Every api enum subclasses str, so to_jsonable's primitive fast path
# serializes them as their BARE VALUE (compact, and exactly what the C++
# client emits — the `__enum__` envelope below only matters for plain
# Enums). A bare value decodes as `str`, which compares EQUAL to its
# str-enum member — so every requirement/taint/phase comparison works —
# but `.value` accesses crash (`taint.effect.value` in an error-message
# path was the differential fuzzer's find, corpus pin seed8505). Coerce
# the known enum-typed dataclass fields back to members at decode; the
# wire bytes are unchanged, so pre-fix senders round-trip identically.
_ENUM_FIELDS: dict[str, dict[str, type]] = {
    "NodeSelectorRequirement": {"operator": api.Operator},
    "LabelSelectorRequirement": {"operator": api.Operator},
    "Taint": {"effect": api.TaintEffect},
    "Toleration": {"effect": api.TaintEffect},
    "TopologySpreadConstraint": {
        "when_unsatisfiable": api.WhenUnsatisfiable,
        "node_affinity_policy": api.NodeInclusionPolicy,
        "node_taints_policy": api.NodeInclusionPolicy,
    },
    "Pod": {"phase": api.PodPhase},
    "Disruption": {"consolidation_policy": api.ConsolidationPolicy},
}


def to_jsonable(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {"__enum__": type(obj).__name__, "value": obj.value}
    if isinstance(obj, Requirements):
        return {
            "__type__": "Requirements",
            "requirements": [
                to_jsonable(r) for r in obj.to_node_selector_requirements()
            ],
        }
    if isinstance(obj, Requirement):
        return to_jsonable(_requirement_to_nsr(obj))
    if isinstance(obj, InstanceType):
        return {
            "__type__": "InstanceType",
            "name": obj.name,
            "requirements": to_jsonable(obj.requirements),
            "offerings": [to_jsonable(o) for o in obj.offerings],
            "capacity": dict(obj.capacity),
            "overhead": to_jsonable(obj.overhead),
        }
    if isinstance(obj, Offering):
        return {
            "__type__": "Offering",
            "requirements": to_jsonable(obj.requirements),
            "price": obj.price,
            "available": obj.available,
            "reservation_capacity": obj.reservation_capacity,
        }
    if dataclasses.is_dataclass(obj):
        out = {"__type__": type(obj).__name__}
        for f in dataclasses.fields(obj):
            if f.name.startswith("_"):
                continue
            out[f.name] = to_jsonable(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    raise TypeError(f"cannot serialize {type(obj).__name__}")


def from_jsonable(data: Any) -> Any:
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [from_jsonable(v) for v in data]
    if isinstance(data, dict):
        if "__enum__" in data:
            return _ENUMS[data["__enum__"]](data["value"])
        tname = data.get("__type__")
        if tname == "Requirements":
            reqs = Requirements()
            nsrs = [from_jsonable(r) for r in data["requirements"]]
            reqs.add(*Requirements.from_node_selector_requirements(nsrs).values())
            return reqs
        if tname == "InstanceType":
            return InstanceType(
                name=data["name"],
                requirements=from_jsonable(data["requirements"]),
                offerings=Offerings(
                    from_jsonable(o) for o in data["offerings"]
                ),
                capacity={k: int(v) for k, v in data["capacity"].items()},
                overhead=from_jsonable(data["overhead"]),
            )
        if tname == "Offering":
            return Offering(
                requirements=from_jsonable(data["requirements"]),
                price=data["price"],
                available=data["available"],
                reservation_capacity=data["reservation_capacity"],
            )
        if tname is not None:
            cls = _REGISTRY[tname]
            kwargs = {
                k: from_jsonable(v)
                for k, v in data.items()
                if k != "__type__"
            }
            for k, enum_cls in _ENUM_FIELDS.get(tname, {}).items():
                v = kwargs.get(k)
                if isinstance(v, str) and not isinstance(v, enum.Enum):
                    kwargs[k] = enum_cls(v)
            return cls(**kwargs)
        return {k: from_jsonable(v) for k, v in data.items()}
    raise TypeError(f"cannot deserialize {type(data).__name__}")


def _requirement_to_nsr(r: Requirement) -> api.NodeSelectorRequirement:
    nsrs = Requirements([r]).to_node_selector_requirements()
    return nsrs[0]
