"""Well-known labels, capacity types, and label policy.

Mirrors the reference's label taxonomy in /root/reference/pkg/apis/v1/labels.go:31-180:
which labels the autoscaler understands natively, which are restricted, and how
deprecated label aliases normalize to their stable names.
"""

from __future__ import annotations

GROUP = "karpenter.sh"

# Architectures / capacity types
ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

# Autoscaler-specific labels
NODEPOOL_LABEL_KEY = f"{GROUP}/nodepool"
# NodeClass back-reference label (reference labels.go:188 NodeClassLabelKey
# builds "<group>/<kind>"; node-class refs are plain names here, so one
# stable key stands in for the group-kind family)
NODECLASS_LABEL_KEY = f"{GROUP}/nodeclass"
NODE_INITIALIZED_LABEL_KEY = f"{GROUP}/initialized"
NODE_REGISTERED_LABEL_KEY = f"{GROUP}/registered"
CAPACITY_TYPE_LABEL_KEY = f"{GROUP}/capacity-type"

# Autoscaler-specific annotations
DO_NOT_DISRUPT_ANNOTATION_KEY = f"{GROUP}/do-not-disrupt"
NODEPOOL_HASH_ANNOTATION_KEY = f"{GROUP}/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION_KEY = f"{GROUP}/nodepool-hash-version"
NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY = f"{GROUP}/nodeclaim-termination-timestamp"
NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY = f"{GROUP}/nodeclaim-min-values-relaxed"
TERMINATION_FINALIZER = f"{GROUP}/termination"

# Kubernetes well-known node labels
HOSTNAME_LABEL_KEY = "kubernetes.io/hostname"
TOPOLOGY_ZONE_LABEL_KEY = "topology.kubernetes.io/zone"
TOPOLOGY_REGION_LABEL_KEY = "topology.kubernetes.io/region"
INSTANCE_TYPE_LABEL_KEY = "node.kubernetes.io/instance-type"
ARCH_LABEL_KEY = "kubernetes.io/arch"
OS_LABEL_KEY = "kubernetes.io/os"
WINDOWS_BUILD_LABEL_KEY = "node.kubernetes.io/windows-build"

# The reservation-id label a provider reports for `reserved` capacity offerings
# (reference: pkg/cloudprovider/types.go ReservationIDLabel is provider-set; we
# standardize one for the in-tree providers).
RESERVATION_ID_LABEL_KEY = f"{GROUP}/reservation-id"

# Domains either prohibited by the kubelet or reserved by the autoscaler
# (reference labels.go:69 RestrictedLabelDomains).
RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

# Sub-domains of the restricted domains that are allowed (labels.go:77).
LABEL_DOMAIN_EXCEPTIONS = frozenset(
    {"kops.k8s.io", "node-role.kubernetes.io", "node-restriction.kubernetes.io"}
)

# Labels in the restricted domains the autoscaler understands natively
# (labels.go:86 WellKnownLabels). Mutable on purpose: providers register their
# own well-known labels (the fake provider adds size/special/integer keys just
# like the reference's fake provider does in fake/instancetype.go:41-47).
WELL_KNOWN_LABELS: set[str] = {
    NODEPOOL_LABEL_KEY,
    TOPOLOGY_ZONE_LABEL_KEY,
    TOPOLOGY_REGION_LABEL_KEY,
    INSTANCE_TYPE_LABEL_KEY,
    ARCH_LABEL_KEY,
    OS_LABEL_KEY,
    CAPACITY_TYPE_LABEL_KEY,
    WINDOWS_BUILD_LABEL_KEY,
}

# Labels that must never be used on NodePools/NodeClaims because they interfere
# with provisioning (labels.go:124 RestrictedLabels).
RESTRICTED_LABELS = frozenset({HOSTNAME_LABEL_KEY})

# Deprecated label aliases -> stable names (labels.go:130 NormalizedLabels).
NORMALIZED_LABELS: dict[str, str] = {
    "failure-domain.beta.kubernetes.io/zone": TOPOLOGY_ZONE_LABEL_KEY,
    "failure-domain.beta.kubernetes.io/region": TOPOLOGY_REGION_LABEL_KEY,
    "beta.kubernetes.io/arch": ARCH_LABEL_KEY,
    "beta.kubernetes.io/os": OS_LABEL_KEY,
    "beta.kubernetes.io/instance-type": INSTANCE_TYPE_LABEL_KEY,
}

# Values the autoscaler expects for specific requirement keys
# (labels.go:105 WellKnownValuesForRequirements).
WELL_KNOWN_VALUES_FOR_REQUIREMENTS: dict[str, frozenset[str]] = {
    CAPACITY_TYPE_LABEL_KEY: frozenset(
        {CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT, CAPACITY_TYPE_RESERVED}
    ),
}


def get_label_domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def is_restricted_node_label(key: str) -> bool:
    """True if the autoscaler should not inject this label onto nodes
    (reference labels.go:163 IsRestrictedNodeLabel)."""
    if key in WELL_KNOWN_LABELS:
        return True
    domain = get_label_domain(key)
    for exception in LABEL_DOMAIN_EXCEPTIONS:
        if domain.endswith(exception):
            return False
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain == restricted or domain.endswith("." + restricted):
            return True
    return key in RESTRICTED_LABELS


def is_restricted_label(key: str) -> str | None:
    """Returns an error string if the label may not be used on NodePools
    (reference labels.go:139 IsRestrictedLabel)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key!r} is restricted; specify a well known label "
            f"or a custom label that does not use a restricted domain"
        )
    return None
