from karpenter_tpu.api import labels
from karpenter_tpu.api.objects import (
    Node,
    NodeClaim,
    NodePool,
    NodeSelectorRequirement,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)

__all__ = [
    "labels",
    "Node",
    "NodeClaim",
    "NodePool",
    "NodeSelectorRequirement",
    "ObjectMeta",
    "Pod",
    "PodAffinityTerm",
    "Taint",
    "Toleration",
    "TopologySpreadConstraint",
    "WeightedPodAffinityTerm",
]
