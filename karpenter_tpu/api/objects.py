"""Domain objects (the L0 layer): the CRD-equivalent types of the framework.

These correspond to the reference's API types — NodePool
(/root/reference/pkg/apis/v1/nodepool.go:284), NodeClaim (nodeclaim.go:141) —
plus the slices of core Kubernetes objects (Pod, Node) the autoscaler consumes.
They are plain dataclasses: the control plane persists them in an in-memory
object store (karpenter_tpu.controllers.kube) the way the reference persists CRs
in the apiserver; the solver consumes them only through the tensor encoder.
"""

from __future__ import annotations

import copy
import itertools
import uuid as uuid_mod
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from karpenter_tpu.utils.resources import ResourceList

# ---------------------------------------------------------------------------
# metadata


_seq = itertools.count()


def new_uid() -> str:
    return str(uuid_mod.UUID(int=(next(_seq) << 64) | uuid_mod.uuid4().int >> 64))


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: list[str] = field(default_factory=list)
    resource_version: int = 0
    owner_uid: Optional[str] = None


# ---------------------------------------------------------------------------
# label selection / affinity primitives


class Operator(str, Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: Operator
    values: list[str] = field(default_factory=list)
    # MinValues: flexibility floor — the minimum number of distinct values the
    # key must retain across surviving instance types (reference
    # nodepool.go NodeSelectorRequirementWithMinValues).
    min_values: Optional[int] = None


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: Operator  # In / NotIn / Exists / DoesNotExist
    values: list[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == Operator.IN:
                if val is None or val not in expr.values:
                    return False
            elif expr.operator == Operator.NOT_IN:
                if val is not None and val in expr.values:
                    return False
            elif expr.operator == Operator.EXISTS:
                if expr.key not in labels:
                    return False
            elif expr.operator == Operator.DOES_NOT_EXIST:
                if expr.key in labels:
                    return False
            else:
                return False
        return True

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


# ---------------------------------------------------------------------------
# taints / tolerations


class TaintEffect(str, Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: TaintEffect
    value: str = ""


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists operator tolerates everything
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: Optional[TaintEffect] = None  # None matches all effects

    def tolerates(self, taint: Taint) -> bool:
        """corev1.Toleration.ToleratesTaint semantics."""
        if self.effect is not None and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# pod scheduling constraints


@dataclass
class NodeSelectorTerm:
    match_expressions: list[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    # OR across terms; the scheduler takes term[0] and relaxes by dropping it
    # (reference preferences.go:74 removeRequiredNodeAffinityTerm).
    required_terms: list[NodeSelectorTerm] = field(default_factory=list)
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: LabelSelector = field(default_factory=LabelSelector)
    namespaces: list[str] = field(default_factory=list)  # empty = pod's namespace
    # selects namespaces by their labels; union with `namespaces`
    # (reference topology.go:503 buildNamespaceList)
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


class WhenUnsatisfiable(str, Enum):
    DO_NOT_SCHEDULE = "DoNotSchedule"
    SCHEDULE_ANYWAY = "ScheduleAnyway"


class NodeInclusionPolicy(str, Enum):
    HONOR = "Honor"
    IGNORE = "Ignore"


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: WhenUnsatisfiable = WhenUnsatisfiable.DO_NOT_SCHEDULE
    label_selector: LabelSelector = field(default_factory=LabelSelector)
    min_domains: Optional[int] = None
    node_affinity_policy: NodeInclusionPolicy = NodeInclusionPolicy.HONOR
    node_taints_policy: NodeInclusionPolicy = NodeInclusionPolicy.IGNORE
    # each key's value from the POD's labels folds into the selector as an
    # In requirement (topology.go:434) — per-deployment spread isolation
    match_label_keys: list[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Pod


class PodPhase(str, Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class Container:
    """The resource-relevant slice of v1.Container: requests, limits, and
    (for init containers) the restart policy that marks a sidecar.
    requests/limits are milli-unit ResourceLists; a resource present only
    in limits acts as its request (reference resources.go:96
    MergeResourceLimitsIntoRequests)."""

    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)
    # "Always" on an INIT container marks a restartable sidecar whose
    # requests ride alongside the main containers (KEP-753)
    restart_policy: Optional[str] = None

    def effective_requests(self) -> ResourceList:
        out = dict(self.requests)
        for k, v in self.limits.items():
            out.setdefault(k, v)
        return out


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requests: ResourceList = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: list[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    host_ports: list[tuple[str, str, int]] = field(default_factory=list)  # (ip, proto, port)
    priority: int = 0
    preemption_policy: str = "PreemptLowerPriority"
    node_name: str = ""  # bound node
    phase: PodPhase = PodPhase.PENDING
    # PVC names used by the pod (volume topology injection; reference
    # volumetopology.go:51)
    volume_claims: list[str] = field(default_factory=list)
    # claim name -> CSI driver (resolved from StorageClass.provisioner by
    # VolumeTopology.inject, like the zone requirements); claims absent
    # here count against the default "" bucket
    volume_drivers: dict[str, str] = field(default_factory=dict)
    scheduling_gates: list[str] = field(default_factory=list)
    # Set by the eviction/termination machinery
    terminating: bool = False
    # Container-level specs (VERDICT r5 missing #1): when any of these are
    # set, the pod's effective `requests` resolve at intake via the
    # Ceiling rule — max(sum(containers)+sidecars, rolling init max) +
    # overhead (reference pkg/utils/resources/resources.go:113).
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)

    def __post_init__(self):
        # Intake-time resolution: an explicitly-populated `requests` wins
        # (it IS the resolved form — codec round-trips stay idempotent);
        # otherwise container-level specs collapse into the ceiling.
        if not self.requests and (
            self.containers or self.init_containers or self.overhead
        ):
            from karpenter_tpu.utils import resources as _res

            self.requests = _res.ceiling(
                self.containers, self.init_containers, self.overhead
            )

    @property
    def uid(self) -> str:
        return self.metadata.uid

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def deep_copy(self) -> "Pod":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Node


@dataclass
class VolumeAttachment:
    """storagev1.VolumeAttachment, reduced to what node termination needs:
    the attach-detach controller (external to this framework, simulated in
    tests) deletes these after unmount; termination blocks instance
    deletion until the node's attachments are gone (reference
    node/termination/controller.go:223-252). volume_name matches the pod's
    volume_claims entries (we key volumes by claim name — no PV objects)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    volume_name: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provider_id: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    ready: bool = False
    unschedulable: bool = False
    # condition type -> status ("True"/"False"/"Unknown"), for repair policies
    conditions: dict[str, str] = field(default_factory=dict)
    # CSINode allocatable equivalent: attachable-volume count per CSI
    # driver (reference volumeusage.go:187); empty = no per-driver limits
    csi_allocatable: dict[str, int] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# NodePool / NodeClaim


class ConsolidationPolicy(str, Enum):
    WHEN_EMPTY = "WhenEmpty"
    WHEN_EMPTY_OR_UNDERUTILIZED = "WhenEmptyOrUnderutilized"


@dataclass
class Budget:
    """Disruption budget (reference nodepool.go Budget): max concurrently
    disrupted nodes, expressed as a count or percent, optionally gated to a
    schedule window and to specific reasons."""

    nodes: str = "10%"  # "<int>" or "<int>%"
    reasons: list[str] = field(default_factory=list)  # empty = all reasons
    schedule: Optional[str] = None  # cron expression
    duration_seconds: Optional[float] = None


@dataclass
class Disruption:
    consolidation_policy: ConsolidationPolicy = ConsolidationPolicy.WHEN_EMPTY_OR_UNDERUTILIZED
    consolidate_after_seconds: float = 0.0
    budgets: list[Budget] = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class NodeClaimTemplateSpec:
    """The NodeClaim template embedded in a NodePool spec."""

    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    node_class_ref: str = "default"
    expire_after_seconds: Optional[float] = None
    termination_grace_period_seconds: Optional[float] = None


@dataclass
class NodePool:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: NodeClaimTemplateSpec = field(default_factory=NodeClaimTemplateSpec)
    disruption: Disruption = field(default_factory=Disruption)
    limits: ResourceList = field(default_factory=dict)
    weight: int = 0
    # Static capacity (feature-gated in the reference): fixed replica count
    replicas: Optional[int] = None
    # status
    status_resources: ResourceList = field(default_factory=dict)
    status_node_count: int = 0
    conditions: dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    node_name: str = ""
    image_id: str = ""
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: dict[str, str] = field(default_factory=dict)
    last_pod_event_time: float = 0.0


@dataclass
class NodeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    resources_requests: ResourceList = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    node_class_ref: str = "default"
    expire_after_seconds: Optional[float] = None
    termination_grace_period_seconds: Optional[float] = None
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def nodepool_name(self) -> Optional[str]:
        from karpenter_tpu.api import labels as l

        return self.metadata.labels.get(l.NODEPOOL_LABEL_KEY)


# ---------------------------------------------------------------------------
# PodDisruptionBudget (the slice eviction/disruption needs)


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: "LabelSelector" = field(default_factory=lambda: LabelSelector())
    # exactly one of these is set; values are "<int>" or "<int>%"
    min_available: Optional[str] = None
    max_unavailable: Optional[str] = None

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Storage (the slice volume topology needs; reference volumetopology.go:43)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # zones from allowedTopologies (empty = no restriction)
    zones: list[str] = field(default_factory=list)
    volume_binding_mode: str = "WaitForFirstConsumer"
    # CSI driver name (StorageClass.provisioner) — per-driver volume-limit
    # accounting keys on it (reference volumeusage.go:187 reads CSINode
    # allocatable per driver); "" = the default/unattributed bucket
    provisioner: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    volume_name: str = ""  # bound PV (empty while unbound)
    # the zone of the bound volume's node affinity (empty while unbound)
    volume_zones: list[str] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name


# Status condition types used across controllers (reference apis/v1/*_status.go)
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_READY = "Ready"
COND_DRIFTED = "Drifted"
COND_EMPTY = "Empty"
COND_CONSOLIDATABLE = "Consolidatable"
COND_CONSISTENT_STATE_FOUND = "ConsistentStateFound"
COND_NODE_REGISTRATION_HEALTHY = "NodeRegistrationHealthy"
COND_NODE_CLASS_READY = "NodeClassReady"
