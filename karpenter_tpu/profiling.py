"""Profiling: the pprof equivalent of the reference operator.

Reference /root/reference/pkg/operator/operator.go:183-199 registers Go
pprof handlers (/debug/pprof/profile, /heap, ...) on the metrics port
behind --enable-profiling. This module provides the same capabilities for
the single-process Python operator:

- StackSampler — a sampling CPU profiler over ``sys._current_frames()``
  (all threads, default 100 Hz). Output is collapsed-stack format
  ("frame;frame;frame count" lines), the interchange format flamegraph
  tooling and pprof both ingest; no signals, no tracing overhead when idle.
- heap_snapshot() — tracemalloc top-N allocation sites (pprof /heap
  analog). tracemalloc is started lazily on first use.
- device_trace() — a context manager around jax.profiler.trace: captures
  an XLA/TPU trace (TensorBoard format) for a solve, the accelerator-side
  analog of the benchmark harness's pprof profiles
  (scheduling_benchmark_test.go:114-160).

The HTTP surface (/debug/pprof/profile?seconds=N, /debug/pprof/heap) is
served by controllers/probes.ProbeServer when Options.enable_profiling is
set, mirroring the reference's flag gate.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from collections import Counter
from typing import Iterator, Optional


class StackSampler:
    """Sampling profiler over every live thread's current stack."""

    def __init__(self, hz: float = 100.0):
        self.hz = hz
        self.samples: Counter[str] = Counter()
        self.total = 0

    def _collect_once(self, skip_idents: frozenset[int]) -> None:
        for ident, frame in sys._current_frames().items():
            if ident in skip_idents:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                f = f.f_back
                depth += 1
            # root-first, like collapsed-stack consumers expect
            self.samples[";".join(reversed(parts))] += 1
            self.total += 1

    def run(self, seconds: float) -> "StackSampler":
        """Sample for the given duration from the calling thread."""
        skip = frozenset({threading.get_ident()})
        interval = 1.0 / self.hz
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            self._collect_once(skip)
            time.sleep(interval)
        return self

    def render_collapsed(self) -> str:
        """Collapsed-stack lines, most-sampled first."""
        return "\n".join(
            f"{stack} {count}"
            for stack, count in self.samples.most_common()
        )

    def render_top(self, n: int = 30) -> str:
        """pprof 'top'-style table of leaf frames."""
        leaves: Counter[str] = Counter()
        for stack, count in self.samples.items():
            leaves[stack.rsplit(";", 1)[-1]] += count
        total = max(self.total, 1)
        lines = [f"samples: {self.total}  rate: {self.hz:.0f} Hz"]
        for frame, count in leaves.most_common(n):
            lines.append(f"{count:8d} {100.0 * count / total:5.1f}%  {frame}")
        return "\n".join(lines)


def profile_cpu(seconds: float = 1.0, hz: float = 100.0) -> StackSampler:
    """Sample all threads for `seconds`; returns the sampler."""
    return StackSampler(hz=hz).run(seconds)


_tracemalloc_started = False
# ThreadingHTTPServer can run two /debug/pprof/heap requests concurrently;
# the start/snapshot/stop sequence must be atomic or one request can call
# take_snapshot after the other stopped tracing (RuntimeError -> 500)
_tracemalloc_lock = threading.Lock()


def heap_snapshot(top: int = 30, keep_tracing: bool = False) -> str:
    """Top allocation sites by retained bytes (pprof /heap analog).
    tracemalloc starts on the first call — earlier allocations are
    invisible, matching the lazy semantics of enabling a heap profiler on
    a running process. Tracing is stopped again after the snapshot unless
    keep_tracing is set (full tracing costs multi-x allocation overhead,
    too expensive to leave on permanently from one debug request); two
    calls therefore show allocations between them only with
    keep_tracing=True on the first."""
    import tracemalloc

    global _tracemalloc_started
    with _tracemalloc_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            _tracemalloc_started = True
        snap = tracemalloc.take_snapshot()
        # stop tracing we own unless asked to keep it (so a keep_tracing
        # call followed by a plain one turns it back off); tracing started
        # by the application itself is left alone
        if _tracemalloc_started and not keep_tracing:
            tracemalloc.stop()
            _tracemalloc_started = False
    all_stats = snap.statistics("lineno")
    total = sum(s.size for s in all_stats)
    lines = [f"heap: {total} bytes traced (since profiling was enabled)"]
    for s in all_stats[:top]:
        frame = s.traceback[0]
        lines.append(
            f"{s.size:12d} B {s.count:8d} objs  "
            f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
        )
    return "\n".join(lines)


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture an XLA/TPU profiler trace (TensorBoard trace-viewer format)
    for the enclosed block. No-op if jax's profiler is unavailable."""
    try:
        import jax

        ctx = jax.profiler.trace(logdir)
    except Exception:  # profiler backend missing: degrade to no-op
        ctx = contextlib.nullcontext()
    with ctx:
        yield


# The per-solve phase breakdown (the Measure defer-timer analog,
# pkg/metrics/constants.go:63) lives in karpenter_tpu.tracing since the
# telemetry PR: TpuScheduler.last_profile is a tracing.Trace — .phases /
# .top_phases() / .render() give the breakdown the old SolveProfile did,
# plus spans, the /debug/solves ring, and the phase metrics.
