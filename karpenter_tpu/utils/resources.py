"""Resource-list arithmetic with exact integer milli-unit quantities.

A ResourceList is a plain dict[str, int] mapping resource name -> milli-units
(see karpenter_tpu.utils.quantity). Semantics mirror the reference helpers in
/root/reference/pkg/utils/resources/resources.go:30-163 (Merge, Subtract, Fits,
Cmp, MaxResources, RequestsForPods).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, Mapping

from karpenter_tpu.utils import quantity

if TYPE_CHECKING:  # pragma: no cover
    from karpenter_tpu.api.objects import Container, Pod

ResourceList = dict[str, int]

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
HUGEPAGES_PREFIX = "hugepages-"

# Resources every provider is expected to report on its instance types
# (reference: pkg/apis/v1/labels.go WellKnownResources).
WELL_KNOWN_RESOURCES = frozenset({CPU, MEMORY, EPHEMERAL_STORAGE, PODS})


def parse_list(spec: Mapping[str, str | int | float]) -> ResourceList:
    """Build a ResourceList from human-readable quantities, e.g. {"cpu": "100m"}."""
    return {name: quantity.parse(v) for name, v in spec.items()}


def merge(*lists: Mapping[str, int]) -> ResourceList:
    """Sum of resource lists (reference resources.go:52 Merge)."""
    result: ResourceList = {}
    for rl in lists:
        for name, v in rl.items():
            result[name] = result.get(name, 0) + v
    return result


def merge_into(dest: ResourceList, src: Mapping[str, int]) -> ResourceList:
    for name, v in src.items():
        dest[name] = dest.get(name, 0) + v
    return dest


def subtract(lhs: Mapping[str, int], rhs: Mapping[str, int]) -> ResourceList:
    """lhs - rhs over lhs's keys (reference resources.go:83 Subtract)."""
    return {name: v - rhs.get(name, 0) for name, v in lhs.items()}


def subtract_from(dest: ResourceList, src: Mapping[str, int]) -> None:
    for name, v in src.items():
        dest[name] = dest.get(name, 0) - v


def max_resources(*lists: Mapping[str, int]) -> ResourceList:
    """Element-wise max (reference resources.go:121 MaxResources)."""
    result: ResourceList = {}
    for rl in lists:
        for name, v in rl.items():
            if name not in result or v > result[name]:
                result[name] = v
    return result


def fits(candidate: Mapping[str, int], total: Mapping[str, int]) -> bool:
    """True if candidate <= total element-wise.

    Mirrors reference resources.go:150 Fits: any negative quantity in `total`
    means nothing fits; resources missing from `total` count as zero.
    """
    for v in total.values():
        if v < 0:
            return False
    for name, v in candidate.items():
        if v > total.get(name, 0):
            return False
    return True


def ceiling(
    containers: Iterable["Container"] = (),
    init_containers: Iterable["Container"] = (),
    overhead: Mapping[str, int] | None = None,
) -> ResourceList:
    """Effective pod requests from container-level specs (reference
    resources.go:113 Ceiling / KEP-753 sidecar semantics):

    - init containers run sequentially: the pod must fit the LARGEST of
      them, each stacked on the restartable (sidecar) init containers that
      started before it and keep running;
    - restartable init containers ("Always") are sidecars: their requests
      ride alongside the main containers for the pod's whole life;
    - the result is max(sum(main) + sum(sidecars), rolling init max),
      plus pod overhead (pod.Spec.Overhead, RuntimeClass);
    - a resource present only in a container's limits acts as its request
      (resources.go:96 MergeResourceLimitsIntoRequests).
    """
    restartable_init: ResourceList = {}
    init_peak: ResourceList = {}
    for c in init_containers:
        reqs = c.effective_requests()
        if c.restart_policy == "Always":
            restartable_init = merge(restartable_init, reqs)
            stacked = dict(restartable_init)
        else:
            stacked = merge(reqs, restartable_init)
        init_peak = max_resources(init_peak, stacked)
    main = merge(*(c.effective_requests() for c in containers))
    total = merge(main, restartable_init)
    total = max_resources(total, init_peak)
    if overhead:
        total = merge(total, overhead)
    return total


def requests_for_pods(pods: Iterable["Pod"]) -> ResourceList:
    """Total requests of a set of pods plus a `pods` count resource
    (reference resources.go:30 RequestsForPods)."""
    pods = list(pods)
    result = merge(*(p.requests for p in pods))
    result[PODS] = len(pods) * 1000
    return result


def is_zero(rl: Mapping[str, int]) -> bool:
    return all(v == 0 for v in rl.values())


def to_string(rl: Mapping[str, int]) -> str:
    if not rl:
        return "{}"
    return "{" + ",".join(f"{k}: {quantity.format_milli(v)}" for k, v in sorted(rl.items())) + "}"
