"""PodDisruptionBudget limits (reference /root/reference/pkg/utils/pdb/
pdb.go:41-160): which pods can be evicted right now, and which block
disruption entirely."""

from __future__ import annotations

import math
from typing import Any, Optional

from karpenter_tpu.api.objects import Pod, PodDisruptionBudget, PodPhase


def _parse_intstr(raw: str, total: int, round_up: bool) -> int:
    raw = raw.strip()
    if raw.endswith("%"):
        pct = float(raw[:-1]) / 100.0
        v = total * pct
        return math.ceil(v) if round_up else math.floor(v)
    return int(raw)


class PDBLimits:
    """pdb.Limits: per-PDB remaining disruption allowance over the current
    pod population."""

    def __init__(self, pdbs: list[PodDisruptionBudget], all_pods: list[Pod]):
        self.pdbs = pdbs
        self._allowed: dict[str, int] = {}
        self._matching: dict[str, list[Pod]] = {}
        for pdb in pdbs:
            matching = [
                p
                for p in all_pods
                if p.namespace == pdb.metadata.namespace
                and pdb.selector.matches(p.metadata.labels)
            ]
            healthy = sum(
                1
                for p in matching
                if p.phase == PodPhase.RUNNING and not p.terminating
            )
            total = len(matching)
            if pdb.max_unavailable is not None:
                max_unavail = _parse_intstr(pdb.max_unavailable, total, round_up=False)
                unavailable = total - healthy
                allowed = max(0, max_unavail - unavailable)
            elif pdb.min_available is not None:
                min_avail = _parse_intstr(pdb.min_available, total, round_up=True)
                allowed = max(0, healthy - min_avail)
            else:
                allowed = total
            self._allowed[pdb.name] = allowed
            self._matching[pdb.name] = matching

    @classmethod
    def from_kube(cls, kube: Any) -> "PDBLimits":
        return cls(kube.list("PodDisruptionBudget"), kube.list("Pod"))

    def _pdbs_for(self, pod: Pod) -> list[PodDisruptionBudget]:
        return [
            pdb
            for pdb in self.pdbs
            if pod.namespace == pdb.metadata.namespace
            and pdb.selector.matches(pod.metadata.labels)
        ]

    def can_evict(self, pod: Pod) -> tuple[bool, Optional[str]]:
        """Whether evicting this pod is allowed right now; reason otherwise
        (pdb.go CanEvictPods)."""
        for pdb in self._pdbs_for(pod):
            if self._allowed.get(pdb.name, 0) <= 0:
                return False, f"pdb {pdb.name!r} prevents pod evictions"
        return True, None

    def record_eviction(self, pod: Pod) -> None:
        for pdb in self._pdbs_for(pod):
            self._allowed[pdb.name] = max(0, self._allowed.get(pdb.name, 0) - 1)

    def is_fully_blocked(self, pod: Pod) -> Optional[str]:
        """Multiple PDBs selecting the same pod make eviction undefined
        (reference treats >1 PDB as a blocking misconfiguration)."""
        matching = self._pdbs_for(pod)
        if len(matching) > 1:
            names = ", ".join(p.name for p in matching)
            return f"pod covered by multiple pdbs ({names})"
        return None
