"""Exact Kubernetes resource-quantity arithmetic.

The reference (karpenter-core) uses k8s.io/apimachinery's resource.Quantity, an
exact decimal type. We represent every quantity as an integer count of
*milli-units* (Python ints are arbitrary precision, so arithmetic is exact):

    parse("100m")  -> 100          (0.1 cores  = 100 milli)
    parse("2")     -> 2000         (2 cores    = 2000 milli)
    parse("1Gi")   -> 1073741824000  (bytes x 1000)

Milli-units are the finest granularity Kubernetes supports for requests, so the
representation is lossless for every valid quantity. Reference semantics:
/root/reference/pkg/utils/resources/resources.go (Cmp/Fits/Merge/Subtract).
"""

from __future__ import annotations

import math
import re
from fractions import Fraction

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}

_QTY_RE = re.compile(r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)(?:[eE](?P<exp>[+-]?\d+))?(?P<suffix>m|Ki|Mi|Gi|Ti|Pi|Ei|k|M|G|T|P|E)?$")


def parse(value: str | int | float) -> int:
    """Parse a Kubernetes quantity string into integer milli-units.

    Non-integral milli amounts round up (k8s canonicalizes by rounding up, so a
    request can never be under-counted).
    """
    if isinstance(value, int):
        return value * 1000
    if isinstance(value, float):
        # Fraction(str(...)) keeps the decimal the caller wrote; Fraction(float)
        # would capture the binary over-approximation (0.1 -> 101 milli).
        return math.ceil(Fraction(str(value)) * 1000)
    m = _QTY_RE.match(value.strip())
    if not m:
        raise ValueError(f"cannot parse quantity {value!r}")
    num = Fraction(m.group("num"))
    if m.group("exp"):
        num *= Fraction(10) ** int(m.group("exp"))
    suffix = m.group("suffix")
    if suffix == "m":
        scaled = num  # already milli
    elif suffix in _BINARY:
        scaled = num * _BINARY[suffix] * 1000
    elif suffix in _DECIMAL:
        scaled = num * _DECIMAL[suffix] * 1000
    else:
        scaled = num * 1000
    if m.group("sign") == "-":
        scaled = -scaled
    return math.ceil(scaled)


def format_milli(millis: int) -> str:
    """Human-readable rendering of a milli-quantity (for logs/errors)."""
    if millis == 0:
        return "0"
    neg = "-" if millis < 0 else ""
    millis = abs(millis)
    if millis % 1000 != 0:
        return f"{neg}{millis}m"
    units = millis // 1000
    for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        base = _BINARY[suffix]
        if units % base == 0 and units >= base:
            return f"{neg}{units // base}{suffix}"
    for suffix in ("E", "P", "T", "G", "M", "k"):
        base = _DECIMAL[suffix]
        if units % base == 0 and units >= base:
            return f"{neg}{units // base}{suffix}"
    return f"{neg}{units}"
