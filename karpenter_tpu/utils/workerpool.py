"""Bounded worker pool: the reconciler-concurrency analog.

The reference scales controller reconcilers with controller-runtime worker
pools (node/termination 100->5000 workers, termination/controller.go:58-60;
disruption queue 100, queue.go:66) and fans independent work out with
k8s.io workqueue.ParallelizeUntil (provisioner.go:153 launches,
scheduler.go:748 candidate scans — the latter became the vectorized TPU
kernel here). This module provides the same primitive for the parts of the
control plane that stay host-side: independent per-object reconciles and
cloud-provider calls.

SimKube CRUD is atomic per op (controllers/kube.py takes a lock around
each op including its watch emit), so concurrent reconciles interact
exactly like controllers against a real apiserver: through optimistic
concurrency, surfacing as Conflict and retried on the next tick.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional


def parallelize_until(
    workers: int, n: int, fn: Callable[[int], None]
) -> list[Optional[Exception]]:
    """k8s.io/client-go workqueue.ParallelizeUntil: run fn(0..n-1) on at
    most `workers` threads, draining every index through ordinary
    failures. Returns the per-index Exception (or None) so the caller
    decides requeue semantics — reconcile errors must not abort sibling
    reconciles. Interrupts (KeyboardInterrupt/SystemExit) DO propagate:
    in the serial path they abort the drain immediately; in the threaded
    path already-submitted indices finish before the interrupt re-raises
    at result consumption."""
    errs: list[Optional[Exception]] = [None] * n
    if n == 0:
        return errs
    if workers <= 1:
        for i in range(n):
            try:
                fn(i)
            # Exception only: KeyboardInterrupt/SystemExit must keep
            # propagating or the control loop becomes un-interruptible
            except Exception as e:
                errs[i] = e
        return errs

    def run(i: int) -> None:
        try:
            fn(i)
        except Exception as e:
            errs[i] = e

    with ThreadPoolExecutor(max_workers=min(workers, n)) as pool:
        list(pool.map(run, range(n)))
    return errs
