"""Structured logging: the zap-equivalent for this framework.

Reference /root/reference/pkg/operator/logging/logging.go: the operator
builds a zap JSON logger (level-gated, named per controller, structured
key/value fields) and every controller logs its decisions through it. Here
the same shape rides the stdlib: one process-wide `Logger` producing one
JSON object per line with `ts`, `level`, `logger` (controller name), `msg`,
and arbitrary structured fields — machine-parseable like the reference's
zap output, silent below the configured level, and capturable in tests via
`capture()`.

Controllers obtain named children with `logger.named("provisioner")`, the
analog of zap's Named(); the Operator wires the level from Options
(`log_level`, env KARPENTER_LOG_LEVEL).
"""

from __future__ import annotations

import io
import json
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}
_ALIASES = {"warning": "warn", "err": "error"}


def _level_no(level: str) -> int:
    name = str(level).lower()
    name = _ALIASES.get(name, name)
    return LEVELS.get(name, 20)


class Logger:
    """A named, level-gated JSON-lines logger."""

    def __init__(
        self,
        name: str = "",
        level: str = "info",
        stream=None,
        clock=None,
        _root: Optional["Logger"] = None,
    ):
        self.name = name
        self._root = _root or self
        if _root is None:
            self._level_no = _level_no(level)
            self._stream = stream or sys.stderr
            self._lock = threading.Lock()
            self._clock = clock
            self._capturing = False

    # -- configuration (root only) ---------------------------------------

    def set_level(self, level: str) -> None:
        # capture() pins the level for the duration of the capture so an
        # Operator constructed inside the block can't silently defeat it
        if getattr(self._root, "_capturing", False):
            return
        self._root._level_no = _level_no(level)

    def set_clock(self, clock) -> None:
        """Use a simulation clock for timestamps (tests, FakeClock)."""
        self._root._clock = clock

    def named(self, name: str) -> "Logger":
        """zap Named(): a child whose records carry `parent.child`."""
        child = Logger(_root=self._root)
        child.name = f"{self.name}.{name}" if self.name else name
        return child

    # -- emission ---------------------------------------------------------

    def _emit(self, level: str, msg: str, fields: dict[str, Any]) -> None:
        root = self._root
        if LEVELS[level] < root._level_no:
            return
        now = root._clock.now() if root._clock is not None else time.time()
        rec = {"ts": round(now, 3), "level": level, "logger": self.name, "msg": msg}
        for k, v in fields.items():
            rec[k] = v if isinstance(v, (str, int, float, bool, type(None))) else str(v)
        line = json.dumps(rec, separators=(",", ":"))
        with root._lock:
            print(line, file=root._stream, flush=False)

    def debug(self, msg: str, **fields: Any) -> None:
        self._emit("debug", msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._emit("info", msg, fields)

    def warn(self, msg: str, **fields: Any) -> None:
        self._emit("warn", msg, fields)

    def error(self, msg: str, **fields: Any) -> None:
        self._emit("error", msg, fields)


# the process-wide root, like the reference's injected context logger
root = Logger(name="karpenter")


@contextmanager
def capture(level: str = "debug"):
    """Route the root logger into a buffer and yield the parsed records —
    the test harness for controller logging.

    Also hooks `threading.excepthook` for the duration: an uncaught
    exception in a background thread (a server connection handler dying,
    a worker-pool task exploding outside its catch) becomes an ERROR
    record named `karpenter.threading` AND lands in
    `records.thread_exceptions`, so a test can assert on it — instead of
    the default behavior, where the traceback prints to the real stderr
    and the test passes in silence."""
    buf = io.StringIO()
    old_stream, old_level = root._stream, root._level_no
    old_clock = root._clock
    root._stream = buf
    root._level_no = _level_no(level)
    root._capturing = True
    old_hook = threading.excepthook
    thread_exceptions: list[dict] = []
    thread_log = root.named("threading")

    def _thread_hook(args):
        info = {
            "thread": getattr(args.thread, "name", "?"),
            "exc_type": getattr(args.exc_type, "__name__", "?"),
            "exc_value": args.exc_value,
        }
        thread_exceptions.append(info)
        thread_log.error(
            "uncaught exception in background thread",
            thread=info["thread"],
            error=f"{info['exc_type']}: {info['exc_value']}",
        )
        # CHAIN the previous hook: inside a racert-instrumented test the
        # previous hook is the race witness — capture() recording the
        # exception must not hide it from witness.assert_no_thread_
        # exceptions(); outside, it keeps pytest's threadexception
        # reporting (or the stderr default) intact.
        old_hook(args)

    threading.excepthook = _thread_hook

    class Records(list):
        def refresh(self):
            self.clear()
            for line in buf.getvalue().splitlines():
                if line.strip():
                    self.append(json.loads(line))
            return self

    records = Records()
    records.thread_exceptions = thread_exceptions
    try:
        yield records
    finally:
        records.refresh()
        threading.excepthook = old_hook
        root._stream = old_stream
        root._level_no = old_level
        root._clock = old_clock
        root._capturing = False
