#!/usr/bin/env python
"""Scaling probe for the solver scan: how does per-pod step time vary with
claim-slot count N, instance-type count I, and pod count? Distinguishes
per-op dispatch overhead (flat in N) from bandwidth (linear in N)."""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=2048)
    ap.add_argument("--types", type=int, default=500)
    ap.add_argument("--slots", type=int, default=0, help="claim slots override")
    args = ap.parse_args()

    import jax

    from bench import build_universe, make_problem
    from karpenter_tpu.solver import tpu_kernel as K
    from karpenter_tpu.solver.tpu import TpuScheduler, _pow2
    from karpenter_tpu.solver.tpu_problem import encode_problem

    its = build_universe(args.types)
    print(f"universe: {len(its)} types")
    node_pool, pods, topo = make_problem(args.pods, its)
    sched = TpuScheduler([node_pool], {"default": its}, topo)
    problem = encode_problem(sched.oracle, pods)
    for p in pods:
        sched.oracle._update_cached_pod_data(p)

    N = args.slots or _pow2(len(pods))
    tb = sched._tables(problem)
    st = sched._init_state(problem, N)
    xs = sched._pod_xs(problem, list(range(len(pods))))
    print(
        f"P={len(pods)} N={N} I={problem.num_types} T={problem.num_templates} "
        f"TW={problem.vocab.total_words} K={problem.vocab.num_keys} "
        f"Gv={len(problem.vgroups)} Gh={len(problem.hgroups)} "
        f"C={problem.ptopo_kind.shape[1]}"
    )

    t0 = time.monotonic()
    out = K.solve_scan(tb, st, xs)
    jax.block_until_ready(out)
    t_compile = time.monotonic() - t0
    print(f"compile+run: {t_compile:.1f}s")

    t0 = time.monotonic()
    st2, kinds, slots, _over, _odo = K.solve_scan(tb, st, xs)
    jax.block_until_ready((st2, kinds, slots))
    t = time.monotonic() - t0
    kinds = np.asarray(kinds)
    n_fail = int(np.sum(kinds == K.KIND_FAIL))
    print(
        f"steady: {t:.3f}s for {xs.valid.shape[0]} steps -> "
        f"{1e6 * t / xs.valid.shape[0]:.0f} us/step, "
        f"{np.sum(np.asarray(xs.valid)) / t:.0f} pods/s "
        f"(claims={int(st2.n_claims)}, fail={n_fail})"
    )


if __name__ == "__main__":
    main()
