#!/usr/bin/env python
"""Benchmark: Scheduler.Solve pods/sec — TPU batched solver vs the in-process
sequential FFD oracle (BASELINE.md).

Default run = the headline config (10k pending pods x 500 instance types,
the reference benchmark's diverse pod mix, scheduling_benchmark_test.go:257)
with a FULL-SIZE oracle baseline run — no capped-baseline extrapolation.
Compile time is reported separately from the steady-state number (the jit
cache persists across solves of the same shape, so a long-running control
plane pays it once).

`--all` additionally measures the five BASELINE.json configs and writes
BENCH_DETAIL.json next to the repo root:
  1. 500 pods x 50 types, resource requests only
  2. 10k pods x 500 types with nodeSelector + taints/tolerations
  3. 5k pods, topology spread + pod anti-affinity across 3 zones
  4. multi-node consolidation sweep over 2k under-utilized nodes
  5. mixed spot/on-demand, 50k pods x 1k instance types
For configs where a full oracle run would take tens of minutes (3, 5) the
baseline is a measured power-law scaling curve fit to full runs at smaller
sizes — measured curve, not a flat ratio from a cap.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": <tpu pods/sec>, "unit": "pods/sec",
   "vs_baseline": <tpu / oracle speedup>}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --- cold start (process start -> first completed solve) -------------------

_COLD_SCRIPT = r"""
import json, sys, time
t0 = time.monotonic()  # process-start proxy: first line of the script
n_pods, n_types = int(sys.argv[1]), int(sys.argv[2])
sys.path.insert(0, ".")
# real-backend-compile accounting lives in ONE place — karpenter_tpu.tracing
# trace_events (compile events fire on persistent-cache hits too; the IR
# tier re-exports the same object)
from karpenter_tpu.tracing import trace_events
from bench import build_universe, make_problem
from karpenter_tpu.solver.tpu import TpuScheduler

its = build_universe(n_types)
pools, ibp, pods, topo = make_problem(n_pods, its)
with trace_events() as ev:
    r = TpuScheduler(pools, ibp, topo).solve(pods)
t1 = time.monotonic()
print(json.dumps({
    "first_solve_seconds": round(t1 - t0, 2),
    "scheduled": sum(len(c.pods) for c in r.new_node_claims),
    "backend_compiles": ev.backend_compiles,
    "cache_hits": ev.cache_hits,
}))
"""


def run_coldstart(n_pods: int, n_types: int, cache_dir: str) -> dict:
    """One subprocess-fresh run: process start -> first completed solve,
    against the given persistent-cache directory."""
    env = dict(os.environ)
    env["KARPENTER_COMPILATION_CACHE_DIR"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", _COLD_SCRIPT, str(n_pods), str(n_types)],
        env=env,
        # the child imports `bench` by name; anchor it to THIS file's repo
        # regardless of the caller's working directory
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-4000:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def bench_coldstart(n_pods: int, n_types: int) -> dict:
    """The cold-start row (ISSUE 8): the same problem measured from a
    fresh process against (a) an empty cache — the compile wall — and
    (b) the cache that run just populated — the warm-from-disk path the
    AOT prewarm makes the common case. The warm run must show zero real
    backend compiles (every compile_or_get served from disk)."""
    with tempfile.TemporaryDirectory(prefix="ktpu-coldbench-") as cache_dir:
        log(f"  cold run ({n_pods} pods x {n_types} types, empty cache)...")
        cold = run_coldstart(n_pods, n_types, cache_dir)
        log(f"    {cold['first_solve_seconds']}s, {cold['backend_compiles']} compiles")
        log("  warm run (same cache)...")
        warm = run_coldstart(n_pods, n_types, cache_dir)
        log(f"    {warm['first_solve_seconds']}s, {warm['backend_compiles']} compiles")
    return {
        "pods": n_pods,
        "types": n_types,
        "cold_first_solve_seconds": cold["first_solve_seconds"],
        "warm_first_solve_seconds": warm["first_solve_seconds"],
        "speedup": round(
            cold["first_solve_seconds"] / max(warm["first_solve_seconds"], 1e-9), 2
        ),
        "cold_backend_compiles": cold["backend_compiles"],
        "warm_backend_compiles": warm["backend_compiles"],
        "warm_cache_hits": warm["cache_hits"],
    }


def build_universe(n_types: int):
    from karpenter_tpu.cloudprovider.kwok import KWOK_FAMILIES, construct_instance_types

    # 1 size => len(families) * 2 os * 2 arch = 12 types
    per_size = len(KWOK_FAMILIES) * 2 * 2
    n_sizes = max(1, (n_types + per_size - 1) // per_size)
    sizes = sorted({1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256} | set(
        range(3, 3 + n_sizes * 3, 3)
    ))[:n_sizes]
    its = construct_instance_types(sizes=sizes)
    return its[:n_types] if len(its) > n_types else its


def make_problem(n_pods: int, its, pods_fn=None, pools_fn=None):
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(42)
    pools = pools_fn() if pools_fn else [fixtures.node_pool(name="default")]
    pods = pods_fn(n_pods) if pods_fn else fixtures.make_diverse_pods(n_pods)
    its_by_pool = {np.name: its for np in pools}
    topo = Topology(pools, its_by_pool, pods)
    return pools, its_by_pool, pods, topo


def time_tpu(n_pods, its, pods_fn=None, pools_fn=None):
    """(steady pods/sec, compile seconds, steady phase totals, kernel
    odometer) — compile measured as first-call minus steady-state; phases
    are the steady run's top-level solve-trace totals (encode/order/
    upload/dispatch/regrow/decode), so bench rows can show WHERE a
    regression landed; the odometer is the steady run's device-truth
    counter block (TpuScheduler.last_odometer)."""
    from karpenter_tpu.solver.tpu import TpuScheduler

    pools, ibp, pods, topo = make_problem(n_pods, its, pods_fn, pools_fn)
    t0 = time.monotonic()
    r = TpuScheduler(pools, ibp, topo).solve(pods)
    first = time.monotonic() - t0
    n_err = len(r.pod_errors)

    pools, ibp, pods, topo = make_problem(n_pods, its, pods_fn, pools_fn)
    sched = TpuScheduler(pools, ibp, topo)
    t0 = time.monotonic()
    r = sched.solve(pods)
    steady = time.monotonic() - t0
    phases = dict(sched.last_profile.top_phases())
    odo = dict(sched.last_odometer or {})
    log(
        f"  tpu: {steady:.2f}s steady ({n_pods / steady:.0f} pods/s), "
        f"compile {max(0.0, first - steady):.1f}s, {n_err} errors, "
        f"{len([c for c in r.new_node_claims if c.pods])} claims, "
        f"{odo.get('steps', 0)} kernel iterations"
    )
    return n_pods / steady, max(0.0, first - steady), phases, odo


def phase_breakdown(phases: dict) -> tuple[dict, dict]:
    """(phase_seconds, phase_shares) rounded for a bench row."""
    total = sum(phases.values()) or 1.0
    return (
        {k: round(v, 3) for k, v in sorted(phases.items())},
        {k: round(v / total, 3) for k, v in sorted(phases.items())},
    )


def odometer_row(odo: dict, n_pods: int) -> dict:
    """The kernel-odometer columns a bench row records — the pinned
    before-number the wave-packing PR (ROADMAP item 1) will be judged
    against: its win must show up as FEWER iterations per pod, not
    shifted phases."""
    steps = int(odo.get("steps", 0))
    return {
        "kernel_iterations": steps,
        "iterations_per_pod": round(steps / max(n_pods, 1), 4),
        "kernel_bulk_steps": int(odo.get("bulk_steps", 0)),
        "kernel_tier_steps": int(odo.get("tier_steps", 0)),
        "kernel_dispatches": int(odo.get("dispatches", 0)),
        "claims_opened": int(odo.get("claims_opened", 0)),
        "claim_slots": int(odo.get("claim_slots", 0)),
    }


# --- perf-regression sentinel (bench.py --check) ---------------------------

# Explicit tolerances, one place. Throughput is the noisiest number on a
# shared 1-core container, so its band is wide; phase SHARES are
# ratio-of-ratios (total-time noise divides out) and iteration counts
# are deterministic for a pinned problem, so those bands are tight.
DEFAULT_TOLERANCES = {
    # current pods/s must be >= baseline * (1 - throughput_drop)
    "throughput_drop": 0.35,
    # a phase's share of the solve may grow at most this factor
    "phase_share_factor": 1.75,
    # shares below this are noise-dominated and never compared
    "phase_share_floor": 0.05,
    # odometer iterations/pod may grow at most this factor (deterministic
    # up to requeue-round composition; 15% covers bucket-edge wiggle)
    "iterations_factor": 1.15,
}


def check_regression(current: dict, baseline: dict, tolerances=None) -> list:
    """Compare one measured bench row against its stored baseline row;
    returns a list of human-readable failure strings (empty = pass).
    Pure and import-safe — tests/test_perf_sentinel.py drives it with
    synthetic rows (including the injected 2x phase-share regression)."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    failures = []
    base_ps = baseline.get("tpu_pods_per_sec")
    cur_ps = current.get("tpu_pods_per_sec")
    if base_ps and cur_ps is not None:
        floor = base_ps * (1.0 - tol["throughput_drop"])
        if cur_ps < floor:
            failures.append(
                f"throughput regressed: {cur_ps:.1f} pods/s < "
                f"{floor:.1f} (baseline {base_ps:.1f} - "
                f"{tol['throughput_drop']:.0%})"
            )
    base_shares = baseline.get("phase_shares") or {}
    for phase, share in (current.get("phase_shares") or {}).items():
        b = base_shares.get(phase)
        if b is None or max(b, share) < tol["phase_share_floor"]:
            continue
        limit = max(b * tol["phase_share_factor"], tol["phase_share_floor"])
        if share > limit:
            failures.append(
                f"phase share regressed: {phase} at {share:.3f} > "
                f"{limit:.3f} (baseline {b:.3f} x "
                f"{tol['phase_share_factor']})"
            )
    base_it = baseline.get("iterations_per_pod")
    cur_it = current.get("iterations_per_pod")
    if base_it and cur_it:
        limit = base_it * tol["iterations_factor"]
        if cur_it > limit:
            failures.append(
                f"kernel iterations regressed: {cur_it} iterations/pod > "
                f"{limit:.4f} (baseline {base_it} x "
                f"{tol['iterations_factor']})"
            )
    return failures


def run_check(current: dict, baseline, baseline_row: str, tolerances=None) -> tuple:
    """(exit_code, report) — 0 pass, 1 regression, 2 no baseline."""
    if not baseline:
        return 2, {
            "ok": False,
            "baseline_row": baseline_row,
            "error": (
                f"no {baseline_row!r} row in BENCH_DETAIL.json — run the "
                "matching bench first to pin a baseline"
            ),
        }
    failures = check_regression(current, baseline, tolerances)
    report = {
        "ok": not failures,
        "baseline_row": baseline_row,
        "failures": failures,
        "tolerances": {**DEFAULT_TOLERANCES, **(tolerances or {})},
        "current": current,
        "baseline": {
            k: baseline.get(k)
            for k in (
                "tpu_pods_per_sec", "phase_shares", "kernel_iterations",
                "iterations_per_pod",
            )
            if k in baseline
        },
    }
    return (1 if failures else 0), report


def time_oracle_full(n_pods, its, pods_fn=None, pools_fn=None):
    from karpenter_tpu.solver.oracle import Scheduler

    pools, ibp, pods, topo = make_problem(n_pods, its, pods_fn, pools_fn)
    t0 = time.monotonic()
    Scheduler(pools, ibp, topo).solve(pods)
    dt = time.monotonic() - t0
    log(f"  oracle (full {n_pods}): {dt:.2f}s ({n_pods / dt:.0f} pods/s)")
    return n_pods / dt


def oracle_curve(sizes, its, pods_fn=None, pools_fn=None):
    """Fit t = a * n^b to full oracle runs at the given sizes; returns a
    predictor n -> pods/sec. A measured scaling curve, not a flat ratio."""
    import math

    pts = []
    for n in sizes:
        ps = time_oracle_full(n, its, pods_fn, pools_fn)
        pts.append((n, n / ps))
    lx = [math.log(n) for n, _ in pts]
    ly = [math.log(t) for _, t in pts]
    nn = len(pts)
    b = (nn * sum(x * y for x, y in zip(lx, ly)) - sum(lx) * sum(ly)) / (
        nn * sum(x * x for x in lx) - sum(lx) ** 2
    )
    a = math.exp((sum(ly) - b * sum(lx)) / nn)

    def pods_per_sec(n: int) -> float:
        t = a * n**b
        log(f"  oracle (curve, t={a:.3g}*n^{b:.2f}): {n} pods -> {t:.1f}s ({n / t:.0f} pods/s)")
        return n / t

    return pods_per_sec


# --- BASELINE.json config pod mixes ---------------------------------------


def pods_requests_only(n):
    from karpenter_tpu.testing import fixtures

    return fixtures.make_generic_pods(n)


def pods_selector_taints(n):
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.api.objects import Toleration
    from karpenter_tpu.testing import fixtures

    zones = ["test-zone-a", "test-zone-b", "test-zone-c", "test-zone-d"]
    out = []
    for i, p in enumerate(fixtures.make_generic_pods(n)):
        p.node_selector = {well_known.TOPOLOGY_ZONE_LABEL_KEY: zones[i % 4]}
        p.tolerations = [Toleration(key="team", operator="Exists")]
        out.append(p)
    return out


def pools_tainted():
    from karpenter_tpu.api.objects import Taint, TaintEffect
    from karpenter_tpu.testing import fixtures

    return [
        fixtures.node_pool(name="default"),
        fixtures.node_pool(
            name="team",
            taints=[Taint(key="team", value="a", effect=TaintEffect.NO_SCHEDULE)],
            weight=10,
        ),
    ]


def pods_topology_heavy(n):
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.testing import fixtures

    half = n // 2
    out = fixtures.make_topology_spread_pods(half, well_known.TOPOLOGY_ZONE_LABEL_KEY)
    out += fixtures.make_pod_anti_affinity_pods(n - half, well_known.HOSTNAME_LABEL_KEY)
    return out


def pools_three_zones():
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.api.objects import NodeSelectorRequirement, Operator
    from karpenter_tpu.testing import fixtures

    return [
        fixtures.node_pool(
            name="default",
            requirements=[
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    Operator.IN,
                    ["test-zone-a", "test-zone-b", "test-zone-c"],
                )
            ],
        )
    ]


def pods_realistic(n):
    """Diverse mix plus a 2% tail of relaxable preference pods — the shape
    the round-2 fallback cliff choked on (one relaxable pod used to drag
    the whole batch to the oracle; the hybrid now partitions per pod)."""
    from karpenter_tpu.testing import fixtures

    pods = fixtures.make_diverse_pods(int(n * 0.98))
    pods += fixtures.make_preference_pods(n - len(pods))
    return pods


def time_hybrid(n_pods, its, pods_fn):
    """Like time_tpu but through the HybridScheduler (per-pod partitioning)."""
    from karpenter_tpu.solver.hybrid import HybridScheduler

    pools, ibp, pods, topo = make_problem(n_pods, its, pods_fn)
    t0 = time.monotonic()
    HybridScheduler(pools, ibp, topo).solve(pods)
    first = time.monotonic() - t0
    pools, ibp, pods, topo = make_problem(n_pods, its, pods_fn)
    s = HybridScheduler(pools, ibp, topo)
    t0 = time.monotonic()
    r = s.solve(pods)
    steady = time.monotonic() - t0
    log(
        f"  hybrid: {steady:.2f}s ({n_pods / steady:.0f} pods/s), used_tpu="
        f"{s.used_tpu} ({s.fallback_reason or 'full kernel'}), "
        f"{len(r.pod_errors)} errors"
    )
    return n_pods / steady, max(0.0, first - steady), bool(s.used_tpu)


def bench_removal_set_sweep(n_nodes: int) -> dict:
    """Removal-set consolidation (disruption/setsweep.py): >= 1000
    arbitrary removal sets per bounded device dispatch at the c4 shape,
    plus the full sweep_sets search against the best-prefix strategies
    it subsumes (docs/consolidation.md)."""
    from karpenter_tpu.controllers.disruption.setsweep import bench_set_sweep

    return bench_set_sweep(n_nodes, 100, 1024)


def bench_epoch_delta(n_nodes: int, n_pods: int) -> dict:
    """The delta-vs-snapshot row (epoch PR acceptance): steady-state wire
    bytes must track *churn + pending pods*, not cluster size, and a
    repeat same-epoch solve must upload zero per-class table bytes.

    Wire half (host-only): a cluster of `n_nodes` StateNodeViews with one
    bound pod each; full-snapshot payload vs the SOLVE_DELTA payload
    after a one-node churn (the epoch client's own encode/diff path).
    Upload half: two solves of the same problem through a shared
    epochs.DeviceTableCache — the repeat's table upload bytes are read
    off the solve trace and must be exactly zero."""
    import json as _json

    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.api.objects import Node, ObjectMeta
    from karpenter_tpu.solver import epochs
    from karpenter_tpu.solver.nodes import StateNodeView
    from karpenter_tpu.solver.service import encode_problem_dict
    from karpenter_tpu.solver.topology import ClusterSource
    from karpenter_tpu.testing import fixtures

    def view(i: int) -> StateNodeView:
        name = f"node-{i:05d}"
        return StateNodeView(
            name=name,
            node_labels={well_known.HOSTNAME_LABEL_KEY: name},
            labels={
                well_known.HOSTNAME_LABEL_KEY: name,
                well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                well_known.TOPOLOGY_ZONE_LABEL_KEY: f"zone-{i % 3}",
                well_known.NODEPOOL_LABEL_KEY: "default",
            },
            available={"cpu": 1500, "memory": 3 * 1024**3 * 1000},
            capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
            initialized=True,
        )

    fixtures.reset_rng(17)
    its = build_universe(144)
    pools = [fixtures.node_pool(name="default")]
    ibp = {"default": its}
    pending = fixtures.make_diverse_pods(n_pods)

    def bound_pod(v):
        p = fixtures.pod(name=f"b-{v.name}", requests={"cpu": "100m"})
        p.node_name = v.name
        return p

    def cluster_of(views, bound):
        # bound pods keep their identity across reconciles (a real
        # control plane re-reads the same objects) — regenerating them
        # would fake churn the delta then has to ship
        nodes = {
            v.name: Node(metadata=ObjectMeta(name=v.name, labels=dict(v.labels)))
            for v in views
        }
        return ClusterSource(
            pods_by_namespace={"default": list(bound)},
            nodes_by_name=nodes,
            namespace_labels={"default": {}},
        )

    views = [view(i) for i in range(n_nodes)]
    bound = [bound_pod(v) for v in views]
    req0 = encode_problem_dict(
        pools, ibp, pending, views, None, None, True, None,
        cluster_of(views, bound),
    )
    snapshot_bytes = len(_json.dumps(req0).encode())
    base = epochs.sections_from_request(req0)
    # churn: one node joins (plus its bound pod) — the steady-state shape
    views2 = views + [view(n_nodes)]
    bound2 = bound + [bound_pod(views2[-1])]
    req1 = encode_problem_dict(
        pools, ibp, pending, views2, None, None, True, None,
        cluster_of(views2, bound2),
    )
    delta = epochs.diff_sections(base, epochs.sections_from_request(req1))
    delta_frame = {
        "client": "bench", "base_epoch": 1, "epoch": 2, "delta": delta,
        "pods_flat": req1["pods_flat"], "options": req1["options"],
        "force_oracle": True,
    }
    delta_bytes = len(_json.dumps(delta_frame).encode())

    from karpenter_tpu.solver.tpu import TpuScheduler

    cache = epochs.DeviceTableCache()

    def upload_solve():
        pools_u, ibp_u, pods_u, topo_u = make_problem(n_pods, its)
        sched = TpuScheduler(pools_u, ibp_u, topo_u, table_cache=cache)
        sched.solve(pods_u)
        return sched.last_profile.counts.get("upload_bytes", 0)

    first_upload = upload_solve()
    repeat_upload = upload_solve()
    row = {
        "nodes": n_nodes,
        "pending_pods": n_pods,
        "snapshot_wire_bytes": snapshot_bytes,
        "delta_wire_bytes": delta_bytes,
        "wire_ratio": round(snapshot_bytes / max(1, delta_bytes), 1),
        "first_upload_bytes": first_upload,
        "repeat_upload_bytes": repeat_upload,
    }
    log(
        f"  epoch: snapshot {snapshot_bytes} B vs delta {delta_bytes} B "
        f"({row['wire_ratio']}x); uploads {first_upload} -> {repeat_upload} B"
    )
    return row


# --- fleet-axis serving (solver/fleet.py) ----------------------------------

_FLEET_SCRIPT = r"""
import json, sys, tempfile, threading, time
sys.path.insert(0, ".")
cfg = json.loads(sys.argv[1])

from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import epochs
from karpenter_tpu.solver.service import SolverClient, SolverServer
from karpenter_tpu.testing import fixtures

def problem(cpu):
    # the shared scan-path fixture — same shape the fleet tests and the
    # fleet[runtime] IR kit measure (fixtures.make_self_spread_pods)
    fixtures.reset_rng(5)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_self_spread_pods(cfg["pods_per_lane"], cpu)
    return pools, {"default": its}, pods

def run(window, clients, per_client, burst=False):
    path = tempfile.mktemp(suffix=".fleetbench.sock")
    # the lane budget tracks the offered concurrency (capped at the
    # prewarmed 8-lane bucket): a FULL window wakes the leader at once,
    # so steady-state coalescing pays ~zero window latency; only a
    # straggler round eats the (small) timeout
    srv = SolverServer(
        path, fleet_window_seconds=window,
        fleet_max_lanes=max(2, min(8, clients)),
        admission=epochs.AdmissionGate(max_inflight=256,
                                       max_cost_seconds=1e9),
    )
    srv.start()
    profiles = [f"{(k % 8) + 1}00m" for k in range(clients)]
    # warm: compile the scan (and, with a window, the vmapped) shapes
    # outside the timed region — steady state is the serving number
    warm_n = min(8, clients) if window else 1
    wb = threading.Barrier(warm_n)
    def warm(cpu):
        c = SolverClient(path, request_timeout=1200.0)
        p = problem(cpu); wb.wait(); c.solve(*p); c.close()
    wt = [threading.Thread(target=warm, args=(profiles[i],), daemon=True)
          for i in range(warm_n)]
    [t.start() for t in wt]; [t.join(timeout=1200) for t in wt]

    barrier = threading.Barrier(clients)
    errs = []
    def client(cpu):
        try:
            c = SolverClient(path, request_timeout=1200.0)
            # pre-connect with retry: a 64-client burst overflows the
            # UDS listen backlog (8); connects must spread, solves burst
            for _ in range(200):
                try:
                    c.connect()
                    break
                except OSError:
                    time.sleep(0.05)
            p = problem(cpu)
            barrier.wait()
            for _ in range(per_client):
                if burst:
                    # synchronized rounds: every client submits together
                    # (aligned reconcile ticks / simulation sweeps — the
                    # arrival pattern whose windows actually fill)
                    barrier.wait()
                c.solve(*p)
            c.close()
        except Exception as e:
            errs.append(repr(e))
    threads = [threading.Thread(target=client, args=(profiles[i],),
                                daemon=True) for i in range(clients)]
    t0 = time.monotonic()
    [t.start() for t in threads]
    [t.join(timeout=1200) for t in threads]
    dt = time.monotonic() - t0
    srv.stop()
    if errs:
        raise RuntimeError(errs[0])
    return round(clients * per_client / dt, 1)

out = {"solo": {}, "coalesced": {}, "solo_burst": {}, "coalesced_burst": {}}
for clients, per_client in cfg["loads"]:
    out["solo"][str(clients)] = run(0.0, clients, per_client)
    out["coalesced"][str(clients)] = run(cfg["window"], clients, per_client)
for clients, per_client in cfg.get("burst_loads", []):
    out["solo_burst"][str(clients)] = run(0.0, clients, per_client,
                                          burst=True)
    out["coalesced_burst"][str(clients)] = run(cfg["window"], clients,
                                               per_client, burst=True)

# kernel dispatch-level lanes/s: the device-path number that transfers
# to accelerator hardware (host encode/decode excluded on both sides)
import numpy as np, jax
import __graft_entry__ as ge
from karpenter_tpu.solver import fleet
from karpenter_tpu.solver import tpu_kernel as K
tb, st, xs, _, _ = ge._small_problem(n_pods=cfg["pods_per_lane"])
B = 8
xs_lanes = [xs._replace(prequests=xs.prequests * (1 + k % 3))
            for k in range(B)]
solo_fn = jax.jit(K.solve_scan)
for x in xs_lanes:
    jax.block_until_ready(solo_fn(tb, st, x)[0])
st_b, xs_b = fleet.stack_lanes([st] * B, xs_lanes)
st_b, xs_b = fleet.shard_lanes(st_b, xs_b)
fleet_fn = fleet.fleet_fn(True, sharded=fleet._mesh_active(B))
jax.block_until_ready(fleet_fn(tb, st_b, xs_b)[0])
N = cfg["kernel_reps"]
t0 = time.monotonic()
for _ in range(N):
    for x in xs_lanes:
        got = solo_fn(tb, st, x)
    jax.block_until_ready(got[0])
t_solo = time.monotonic() - t0
t0 = time.monotonic()
for _ in range(N):
    got = fleet_fn(tb, st_b, xs_b)
jax.block_until_ready(got[0])
t_coal = time.monotonic() - t0
out["kernel_lane_solves_per_sec"] = {
    "solo": round(N * B / t_solo, 1),
    "coalesced": round(N * B / t_coal, 1),
    "speedup": round(t_solo / t_coal, 2),
}
out["devices"] = jax.device_count()
print(json.dumps(out))
"""


def bench_fleet(quick: bool) -> dict:
    """The fleet-axis serving row (solver/fleet.py): solves/sec through
    ONE SolverServer at 1/8/64 concurrent clients, coalesced (batch
    window -> one vmapped dispatch per round) vs the solo-dispatch
    baseline (fleet disabled), on both a 1-device and an 8-virtual-
    device `fleet` mesh, plus the kernel dispatch-level lanes/sec.

    Honesty note for this 1-core CPU container: the vmapped lanes'
    tensor work SERIALIZES on the single core, so the measured speedup
    is only the dispatch-overhead amortization (~1.2-1.6x at the kernel
    level); on a real multi-chip mesh the lane axis shards with zero
    collectives (dryrun_multichip phase 4) and the win scales with the
    device count. The row records both device configs so the hardware
    number lands in the same schema."""
    row: dict[str, dict] = {}
    for ndev in (1,) if quick else (1, 8):
        if ndev == 1:
            loads = [(1, 3), (4, 2)] if quick else [(1, 6), (8, 4), (64, 1)]
            burst = [] if quick else [(8, 4)]
        else:
            # the virtual 8-device mesh shares ONE core: free-running
            # clients form partial windows whose every pow-2 lane bucket
            # compiles its own SHARDED program mid-flight — a compile
            # storm that blows client deadlines without measuring
            # anything real. Burst arrivals fill the window, so one
            # warmed (B=8) sharded shape serves the whole run — the only
            # honest serving measurement this box can make on a mesh
            # (steady-arrival behavior is covered by the 1-device rows).
            loads = [(1, 3)]
            burst = [(8, 2)]
        cfg = {
            "loads": loads,
            # synchronized-burst arrivals (aligned reconcile ticks,
            # simulation sweeps, setsweep proposal rounds): the pattern
            # whose windows actually fill — free-running clients on this
            # 1-core box drift apart by a full host-encode each, so
            # their lanes can never arrive inside one window
            "burst_loads": burst,
            "window": 0.02,
            "pods_per_lane": 8,
            "kernel_reps": 10 if quick else 30,
        }
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={ndev}")
        env["XLA_FLAGS"] = " ".join(flags)
        log(f"  fleet: {ndev}-device mesh, loads {loads} ...")
        out = subprocess.run(
            [sys.executable, "-c", _FLEET_SCRIPT, json.dumps(cfg)],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=3600,
        )
        if out.returncode != 0:
            raise RuntimeError(out.stderr[-4000:])
        got = json.loads(out.stdout.strip().splitlines()[-1])
        log(
            f"    solo {got['solo']} vs coalesced {got['coalesced']} "
            f"solves/s; kernel lanes/s {got['kernel_lane_solves_per_sec']}"
        )
        row[f"devices_{ndev}"] = got
    return row


def merge_detail(rows: dict) -> None:
    """Merge bench rows into BENCH_DETAIL.json without clobbering the
    other configs (the --consolidation section updates its row next to
    the full --all run's)."""
    try:
        with open("BENCH_DETAIL.json") as f:
            detail = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        detail = {}
    detail.update(rows)
    with open("BENCH_DETAIL.json", "w") as f:
        json.dump(detail, f, indent=2)
    log("wrote BENCH_DETAIL.json")


def bench_consolidation_sweep(n_nodes: int) -> dict:
    """Config 4: one batched device sweep over candidate-prefix removal sets
    vs the reference's sequential binary search (multinodeconsolidation.go:116)."""
    from karpenter_tpu.controllers.disruption.sweep import bench_sweep

    return bench_sweep(n_nodes)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--types", type=int, default=500)
    ap.add_argument("--all", action="store_true", help="run all BASELINE configs")
    ap.add_argument("--quick", action="store_true", help="tiny shapes (CI smoke)")
    ap.add_argument(
        "--consolidation",
        action="store_true",
        help="removal-set sweep section only (writes c8 into BENCH_DETAIL.json)",
    )
    ap.add_argument(
        "--cold",
        action="store_true",
        help=(
            "cold-start section only: subprocess-fresh process-start -> "
            "first-solve, empty vs warm disk cache (writes c9 into "
            "BENCH_DETAIL.json)"
        ),
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help=(
            "fleet-axis serving section only: solves/sec at concurrent "
            "clients through one SolverServer, coalesced vs solo "
            "dispatch, 1- and 8-device mesh (writes c11 into "
            "BENCH_DETAIL.json)"
        ),
    )
    ap.add_argument(
        "--epoch",
        action="store_true",
        help=(
            "epoch delta-vs-snapshot section only: steady-state wire "
            "bytes + repeat same-epoch upload bytes (writes c10 into "
            "BENCH_DETAIL.json)"
        ),
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help=(
            "perf-regression sentinel: measure the headline shape (or "
            "the --quick smoke shape) and compare throughput, phase "
            "shares, and odometer iterations/pod against the stored "
            "BENCH_DETAIL.json row under explicit tolerances; exit 1 on "
            "regression, 2 when no baseline row exists"
        ),
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="with --check: print the full indented report (default: one line)",
    )
    ap.add_argument(
        "--inject-phase-regression",
        metavar="PHASE:FACTOR",
        default=None,
        help=(
            "testing hook for --check: multiply the measured share of "
            "PHASE by FACTOR before comparing (the acceptance gate's "
            "synthetic 2x phase-share regression)"
        ),
    )
    args = ap.parse_args()

    detail: dict[str, dict] = {}

    if args.check:
        key = "quick_smoke" if args.quick else "headline_diverse"
        try:
            with open("BENCH_DETAIL.json") as f:
                baseline = json.load(f).get(key)
        except (FileNotFoundError, json.JSONDecodeError):
            baseline = None
        n_pods, n_types = (200, 144) if args.quick else (args.pods, args.types)
        # shape guard: throughput and iterations/pod scale with problem
        # size, so a --check at a different shape than the baseline row's
        # would flag (or mask) regressions for shape reasons — that is a
        # missing-baseline situation (exit 2), not a comparison
        if baseline is not None and "pods" in baseline:
            if (baseline["pods"], baseline.get("types")) != (n_pods, n_types):
                code, report = 2, {
                    "ok": False,
                    "baseline_row": key,
                    "error": (
                        f"baseline row {key!r} was measured at "
                        f"{baseline['pods']} pods x {baseline.get('types')} "
                        f"types, but --check is measuring {n_pods} x "
                        f"{n_types} — re-pin the baseline at this shape "
                        "or drop the --pods/--types override"
                    ),
                }
                print(json.dumps(report, indent=2 if args.json else None))
                sys.exit(code)
        log(f"== check: {n_pods} pods x {n_types} types vs {key} ==")
        its = build_universe(n_types)
        tpu_ps, _comp, phases, odo = time_tpu(n_pods, its)
        _seconds, shares = phase_breakdown(phases)
        current = {
            "tpu_pods_per_sec": round(tpu_ps, 1),
            "phase_shares": shares,
            **odometer_row(odo, n_pods),
        }
        if args.inject_phase_regression:
            # deterministic synthetic regression: the phase's share is set
            # to FACTOR x the BASELINE share (anchoring to the baseline —
            # not the drifting current measurement — makes the acceptance
            # gate's 2x injection fail by exactly 2.0 vs the 1.75x band)
            phase, factor = args.inject_phase_regression.split(":", 1)
            anchor = (baseline or {}).get("phase_shares", {}).get(
                phase, current["phase_shares"].get(phase, 0.0)
            )
            current["phase_shares"] = dict(current["phase_shares"])
            current["phase_shares"][phase] = round(anchor * float(factor), 3)
            current["injected"] = args.inject_phase_regression
        code, report = run_check(current, baseline, key)
        print(json.dumps(report, indent=2 if args.json else None))
        sys.exit(code)

    if args.fleet:
        log("== fleet: coalesced vs solo dispatch through one SolverServer ==")
        row = bench_fleet(args.quick)
        merge_detail({"c11_fleet_throughput": row})
        print(json.dumps(row, indent=2))
        return

    if args.epoch:
        n_nodes, n_pods = (200, 48) if args.quick else (2000, 200)
        log(f"== epoch: delta vs snapshot wire+upload bytes ({n_nodes} nodes) ==")
        row = bench_epoch_delta(n_nodes, n_pods)
        merge_detail({"c10_epoch_delta_wire": row})
        print(json.dumps(row, indent=2))
        return

    if args.cold:
        # --quick mirrors tests/test_compilecache.py's shape (48 diverse
        # pods, two KWOK sizes): the smallest problem that compiles the
        # full runs-path program set, and one that stays CPU-tractable —
        # larger diverse shapes execute minutes-slow off-chip
        n_pods, n_types = (48, 24) if args.quick else (args.pods, args.types)
        log(f"== cold start: process start -> first solve ({n_pods} x {n_types}) ==")
        row = bench_coldstart(n_pods, n_types)
        merge_detail({"c9_coldstart": row})
        print(json.dumps(row, indent=2))
        return

    if args.consolidation:
        log("== consolidation: removal-set sweep over 2k nodes ==")
        row = bench_removal_set_sweep(2000)
        merge_detail({"c8_removal_set_sweep_2k": row})
        print(json.dumps(row, indent=2))
        return

    if args.quick:
        its = build_universe(144)
        tpu_ps, compile_s, phases, odo = time_tpu(200, its)
        oracle_ps = time_oracle_full(200, its)
        seconds, shares = phase_breakdown(phases)
        # the smoke row is a real baseline: bench --check --quick and the
        # wave-packing PR's before-number both read it (merge-not-clobber)
        merge_detail({
            "quick_smoke": {
                "pods": 200,
                "types": 144,
                "tpu_pods_per_sec": round(tpu_ps, 1),
                "oracle_pods_per_sec": round(oracle_ps, 1),
                "speedup": round(tpu_ps / oracle_ps, 2),
                "phase_seconds": seconds,
                "phase_shares": shares,
                **odometer_row(odo, 200),
            }
        })
        print(json.dumps({
            "metric": "Scheduler.Solve pods/sec at 200 pending x 144 types (quick)",
            "value": round(tpu_ps, 1), "unit": "pods/sec",
            "vs_baseline": round(tpu_ps / oracle_ps, 2),
        }))
        return

    if args.all:
        log("== config 1: 500 pods x 50 types, requests only ==")
        its = build_universe(50)
        # production entry: the hybrid routes small topology-free batches
        # to the oracle at the measured crossover (SchedulerOptions
        # .tpu_min_pods) — a 500-pod tick must never be slowed by the TPU
        hyb_ps, comp, used_tpu = time_hybrid(500, its, pods_requests_only)
        orc = time_oracle_full(500, its, pods_requests_only)
        from karpenter_tpu.solver.oracle import SchedulerOptions

        detail["c1_500x50_requests_only"] = {
            "tpu_pods_per_sec": round(hyb_ps, 1), "oracle_pods_per_sec": round(orc, 1),
            "speedup": round(hyb_ps / orc, 2), "compile_seconds": round(comp, 1),
            "routed_to_oracle": not used_tpu,
            "crossover_pods": SchedulerOptions().tpu_min_pods,
            "baseline_kind": "full oracle run (hybrid routes below crossover)",
        }

        log("== config 2: 10k x 500, nodeSelector + taints/tolerations ==")
        its = build_universe(500)
        tpu_ps, comp, _, _ = time_tpu(10_000, its, pods_selector_taints, pools_tainted)
        orc_fn = oracle_curve([1000, 2000, 4000], its, pods_selector_taints, pools_tainted)
        orc = orc_fn(10_000)
        detail["c2_10kx500_selector_taints"] = {
            "tpu_pods_per_sec": round(tpu_ps, 1), "oracle_pods_per_sec": round(orc, 1),
            "speedup": round(tpu_ps / orc, 2), "compile_seconds": round(comp, 1),
            "baseline_kind": "power-law curve from full runs at 1k/2k/4k",
        }

        log("== config 3: 5k topology-heavy (spread + anti, 3 zones) ==")
        its = build_universe(500)
        tpu_ps, comp, _, _ = time_tpu(5_000, its, pods_topology_heavy, pools_three_zones)
        orc_fn = oracle_curve([500, 1000, 2000], its, pods_topology_heavy, pools_three_zones)
        orc = orc_fn(5_000)
        detail["c3_5k_topology_heavy"] = {
            "tpu_pods_per_sec": round(tpu_ps, 1), "oracle_pods_per_sec": round(orc, 1),
            "speedup": round(tpu_ps / orc, 2), "compile_seconds": round(comp, 1),
            "baseline_kind": "power-law curve from full runs at 500/1k/2k",
        }

        log("== config 4: consolidation sweep over 2k nodes ==")
        try:
            detail["c4_consolidation_sweep_2k"] = bench_consolidation_sweep(2000)
        except Exception as e:  # pragma: no cover - report, don't die
            detail["c4_consolidation_sweep_2k"] = {"error": str(e)}

        log("== config 8: removal-set sweep over 2k nodes ==")
        try:
            detail["c8_removal_set_sweep_2k"] = bench_removal_set_sweep(2000)
        except Exception as e:  # pragma: no cover - report, don't die
            detail["c8_removal_set_sweep_2k"] = {"error": str(e)}

        log("== config 7 (extra): single-node consolidation, 1k nodes ==")
        try:
            from karpenter_tpu.controllers.disruption.sweep import (
                bench_single_sweep,
            )

            detail["c7_single_node_sweep_1k"] = bench_single_sweep(1000, 100)
        except Exception as e:  # pragma: no cover - report, don't die
            detail["c7_single_node_sweep_1k"] = {"error": str(e)}

        log("== config 6 (extra): realistic mix — 2% relaxable pods ==")
        its = build_universe(500)
        tpu_ps, comp, used_tpu = time_hybrid(10_000, its, pods_realistic)
        orc_fn = oracle_curve([1000, 2000], its, pods_realistic)
        orc = orc_fn(10_000)
        detail["c6_realistic_mix_10k"] = {
            "tpu_pods_per_sec": round(tpu_ps, 1), "oracle_pods_per_sec": round(orc, 1),
            "speedup": round(tpu_ps / orc, 2), "compile_seconds": round(comp, 1),
            "used_tpu_for_bulk": used_tpu,
            "baseline_kind": "power-law curve from full runs at 1k/2k",
        }

        log("== config 5: 50k x 1k, mixed spot/on-demand ==")
        its = build_universe(1000)
        tpu_ps, comp, _, _ = time_tpu(50_000, its)
        orc_fn = oracle_curve([1000, 2000, 4000], its)
        orc = orc_fn(50_000)
        detail["c5_50kx1k_mixed"] = {
            "tpu_pods_per_sec": round(tpu_ps, 1), "oracle_pods_per_sec": round(orc, 1),
            "speedup": round(tpu_ps / orc, 2), "compile_seconds": round(comp, 1),
            "baseline_kind": "power-law curve from full runs at 1k/2k/4k",
        }

    # --- headline: diverse mix, FULL oracle baseline ---------------------
    log("== headline: diverse mix, full-size oracle baseline ==")
    its = build_universe(args.types)
    log(f"universe: {len(its)} instance types")
    tpu_ps, compile_s, phases, odo = time_tpu(args.pods, its)
    oracle_ps = time_oracle_full(args.pods, its)
    # per-phase breakdown of the steady headline run (tracing top-level
    # spans): future bench rows show WHERE a regression landed — encode,
    # upload, device dispatch, or decode — not just that one did
    seconds, shares = phase_breakdown(phases)
    detail["headline_diverse"] = {
        "pods": args.pods,
        "types": len(its),
        "tpu_pods_per_sec": round(tpu_ps, 1),
        "oracle_pods_per_sec": round(oracle_ps, 1),
        "speedup": round(tpu_ps / oracle_ps, 2),
        "compile_seconds": round(compile_s, 1),
        "baseline_kind": "full oracle run",
        "phase_seconds": seconds,
        "phase_shares": shares,
        # the odometer columns: iterations/pod is the pinned before-number
        # the wave-packing PR's --check gate compares against
        **odometer_row(odo, args.pods),
    }

    # merge-not-clobber: the default (headline-only) run updates its row
    # next to the --all configs' instead of erasing them
    merge_detail(detail)

    print(
        json.dumps(
            {
                "metric": (
                    f"Scheduler.Solve pods/sec at {args.pods} pending x "
                    f"{len(its)} instance types (KWOK, diverse mix; "
                    "full-size oracle baseline, compile excluded — "
                    f"{round(compile_s, 1)}s one-time)"
                ),
                "value": round(tpu_ps, 1),
                "unit": "pods/sec",
                "vs_baseline": round(tpu_ps / oracle_ps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
