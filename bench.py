#!/usr/bin/env python
"""Benchmark: Scheduler.Solve pods/sec — TPU batched solver vs the in-process
sequential FFD oracle (BASELINE.md).

Shape mirrors the reference benchmark harness
(/root/reference/pkg/controllers/provisioning/scheduling/
scheduling_benchmark_test.go): the diverse pod mix (generic / zonal TSC /
hostname TSC / zonal self-affinity / hostname anti-affinity) against a
KWOK-generated instance-type universe.

Prints ONE JSON line:
  {"metric": ..., "value": <tpu pods/sec>, "unit": "pods/sec",
   "vs_baseline": <tpu / oracle speedup>}

The oracle baseline is measured at min(pods, baseline-cap) pods — Python FFD
throughput degrades with scale, so capping *understates* the speedup
(conservative).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_universe(n_types: int):
    from karpenter_tpu.cloudprovider.kwok import KWOK_FAMILIES, construct_instance_types

    # 1 size => len(families) * 2 os * 2 arch = 12 types
    per_size = len(KWOK_FAMILIES) * 2 * 2
    n_sizes = max(1, (n_types + per_size - 1) // per_size)
    sizes = sorted({1, 2, 4, 8, 16, 32, 48, 64, 96, 128, 192, 256} | set(
        range(3, 3 + n_sizes * 3, 3)
    ))[:n_sizes]
    its = construct_instance_types(sizes=sizes)
    return its[:n_types] if len(its) > n_types else its


def make_problem(n_pods: int, its):
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(42)
    node_pool = fixtures.node_pool(name="default")
    pods = fixtures.make_diverse_pods(n_pods)
    topo = Topology([node_pool], {"default": its}, pods)
    return node_pool, pods, topo


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--types", type=int, default=500)
    ap.add_argument("--baseline-cap", type=int, default=2_000)
    ap.add_argument("--quick", action="store_true", help="tiny shapes (CI smoke)")
    args = ap.parse_args()
    if args.quick:
        args.pods, args.types, args.baseline_cap = 200, 144, 200

    from karpenter_tpu.solver.oracle import Scheduler
    from karpenter_tpu.solver.tpu import TpuScheduler

    its = build_universe(args.types)
    log(f"universe: {len(its)} instance types")

    # --- TPU: compile pass, then steady-state measurement ---------------
    node_pool, pods, topo = make_problem(args.pods, its)
    t0 = time.monotonic()
    tpu = TpuScheduler([node_pool], {"default": its}, topo)
    r = tpu.solve(pods)
    t_compile = time.monotonic() - t0
    log(
        f"tpu warmup: {len(r.new_node_claims)} claims, "
        f"{len(r.pod_errors)} errors, {t_compile:.1f}s (incl. compile)"
    )

    node_pool, pods, topo = make_problem(args.pods, its)
    t0 = time.monotonic()
    tpu = TpuScheduler([node_pool], {"default": its}, topo)
    r = tpu.solve(pods)
    t_tpu = time.monotonic() - t0
    tpu_ps = args.pods / t_tpu
    log(f"tpu solve: {t_tpu:.2f}s -> {tpu_ps:.0f} pods/sec")

    # --- oracle baseline -------------------------------------------------
    n_base = min(args.pods, args.baseline_cap)
    node_pool, pods_b, topo_b = make_problem(n_base, its)
    oracle = Scheduler([node_pool], {"default": its}, topo_b)
    t0 = time.monotonic()
    rb = oracle.solve(pods_b)
    t_oracle = time.monotonic() - t0
    oracle_ps = n_base / t_oracle
    log(
        f"oracle baseline ({n_base} pods): {t_oracle:.2f}s -> "
        f"{oracle_ps:.0f} pods/sec ({len(rb.new_node_claims)} claims)"
    )

    print(
        json.dumps(
            {
                "metric": (
                    f"Scheduler.Solve pods/sec at {args.pods} pending x "
                    f"{len(its)} instance types (KWOK, diverse mix)"
                ),
                "value": round(tpu_ps, 1),
                "unit": "pods/sec",
                "vs_baseline": round(tpu_ps / oracle_ps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
